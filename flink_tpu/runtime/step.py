"""Compiled SPMD step functions.

A Flink job runs thousands of task threads pulling records through Netty
(SURVEY §3.2). Here a pipeline stage compiles to ONE jitted SPMD function:

    step(state, batch, watermark) -> (state', fires)

executed over the mesh with `shard_map`: every device applies the stage's
stateless chain, masks the lanes whose key group it owns (replicate-and-mask
exchange, see parallel/mesh.py), updates its shard of windowed state, and
evaluates due window fires. The checkpoint barrier of the reference
(BarrierBuffer alignment) is simply the step boundary: between two step
invocations ALL state is consistent and snapshottable.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flink_tpu.core.compat import shard_map
from flink_tpu.core.keygroups import assign_to_key_group
from flink_tpu.ops import window_kernels as wk
from flink_tpu.ops.hashing import route_hash
from flink_tpu.parallel.mesh import SHARD_AXIS, MeshContext


@dataclass
class WindowStageSpec:
    """Static config of one keyed-window pipeline stage."""

    win: wk.WindowSpec
    red: wk.ReduceSpec
    capacity_per_shard: int = 1 << 16
    probe_len: int = 16
    # jnp-traceable pre-keyed chain: (values_dict, ts, valid) -> (value, ts, valid)
    # applied on-device before keying (fused maps/filters).
    pre: Optional[Callable] = None
    # "hash" (open-addressing SlotTable) or "direct" (key == slot for
    # bounded non-negative int keys; see wk.init_state layout="direct")
    layout: str = "hash"
    # duplicate-key collapse before the state scatter (wk.update
    # precombine): sort + segmented-scan per (slot, pane), unique-index
    # rep scatters. Only built-in reducers take it; resolved from
    # pipeline.update-precombine by the executor.
    precombine: bool = False
    # packed state planes (wk.init_state packed): touched bits ride a
    # trailing accumulator column — one scatter/sweep maintains both.
    # Resolved from state.packed-planes by the executor (platform-gated
    # auto); only wk.packed_eligible reduce specs take it.
    packed: bool = False


def init_sharded_state(ctx: MeshContext, spec: WindowStageSpec):
    """Per-shard window state stacked on a leading [n_shards] axis.

    Changelog tracking (kg_dirty, sized to the key-group space) is always
    on: the per-batch cost is one route-hash + one bool scatter, and the
    bits are what lets an incremental checkpoint fetch/serialize only the
    key groups that changed (flink_tpu/checkpointing/)."""
    def one(_):
        return wk.init_state(spec.capacity_per_shard, spec.probe_len,
                             spec.win, spec.red, layout=spec.layout,
                             n_key_groups=ctx.max_parallelism,
                             packed=spec.packed)

    states = [one(i) for i in range(ctx.n_shards)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)
    return jax.device_put(stacked, ctx.state_sharding)


def build_window_step(ctx: MeshContext, spec: WindowStageSpec):
    """Compile the stage into a jitted SPMD step over the mesh."""
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh

    def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid, wm):
        # state leaves arrive with their leading [1] shard axis; drop it.
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        if spec.pre is not None:
            values, ts, valid = spec.pre(values, ts, valid)
        kg = assign_to_key_group(route_hash(hi, lo, jnp), maxp, jnp)
        mine = valid & (kg >= kg_start.astype(jnp.uint32)) & (
            kg <= kg_end.astype(jnp.uint32)
        )
        state, _, _ = wk.update(state, spec.win, spec.red, hi, lo, ts,
                                values, mine,
                                direct=spec.layout == "direct", kg=kg,
                                precombine=spec.precombine)
        state, fires = wk.advance_and_fire(state, spec.win, spec.red, wm[0])
        state = jax.tree_util.tree_map(lambda x: x[None], state)
        fires = jax.tree_util.tree_map(lambda x: x[None], fires)
        return state, fires

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS),  # state (leading shard axis)
            P(SHARD_AXIS),  # kg_start
            P(SHARD_AXIS),  # kg_end
            P(), P(), P(), P(), P(),  # batch replicated
            P(SHARD_AXIS),  # per-shard watermark
        ),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def step(state, hi, lo, ts, values, valid, wm):
        """wm: int32[n_shards] watermark per shard (usually identical).
        State is DONATED: XLA updates the 100MB+ shard arrays in place
        instead of copy-on-write; callers must not reuse the old state."""
        return sharded(state, starts, ends, hi, lo, ts, values, valid, wm)

    return step


def mask_update_shard(state, spec: WindowStageSpec, kg_start, kg_end,
                      hi, lo, ts, values, valid, wm, maxp: int,
                      insert: bool = True, kg_fill: bool = False,
                      clear_rows=None, kg_res=None):
    """Shared per-shard body for the mask (replicated-batch) route: hash
    to key groups, mask to owned groups, apply the window update, and
    advance the shard watermark. Used by the single step AND the K-fused
    megastep scan bodies so the mask semantics cannot diverge (the
    exchange route shares exchange_update_shard the same way). ``wm`` is
    this batch's watermark scalar. Returns (state', activity,
    kg_fill_counts); the kg_fill counts (observability.kg-stats skew
    telemetry) are computed INSIDE wk.update so they ride the shared
    pre-combine sort with the other scatter consumers, statically
    compiled out to a zero-length array when off. ``clear_rows`` folds
    the fused-fire scan's deferred purge into the update's ring-reset
    sweep (wk.update). ``kg_res`` (bool ``[maxp]``, tiered state) is the
    replicated HBM-residency mask wk.update diverts cold-group lanes
    around the table with."""
    import dataclasses as _dc

    if spec.pre is not None:
        values, ts, valid = spec.pre(values, ts, valid)
    kg = assign_to_key_group(route_hash(hi, lo, jnp), maxp, jnp)
    mine = valid & (kg >= kg_start.astype(jnp.uint32)) & (
        kg <= kg_end.astype(jnp.uint32)
    )
    state, activity, kgf = wk.update(
        state, spec.win, spec.red, hi, lo, ts, values, mine,
        insert=insert, direct=spec.layout == "direct", kg=kg,
        precombine=spec.precombine, kg_fill=maxp if kg_fill else 0,
        clear_rows=clear_rows, kg_res=kg_res,
    )
    state = _dc.replace(state, watermark=jnp.maximum(state.watermark, wm))
    return state, activity, kgf


def build_window_update_step(ctx: MeshContext, spec: WindowStageSpec,
                             insert: bool = True,
                             kg_fill: bool = False,
                             tiered: bool = False):
    """Update-only half of the window step: apply a micro-batch and advance
    the shard watermark, but do NOT evaluate fires. The reference evaluates
    timers on every watermark advance (HeapInternalTimerService), but a
    window only becomes due when the watermark crosses a pane boundary —
    once per slide interval, i.e. once in ~hundreds of micro-batches. The
    host computes the watermark, so it knows exactly when that happens and
    calls the fire step (build_window_fire_step) only then. Between
    boundaries every step is sync-free: state is donated, nothing is read
    back, and dispatch overlaps device compute.

    ``insert=False`` builds the lookup-only FAST variant (wk.update's
    insert flag): same state layout, so the executor switches between the
    two compiled steps per micro-batch at zero cost, driven by the lagged
    activity signal in the monitoring output.

    ``tiered=True`` appends one trailing ``kg_res`` operand (replicated
    bool ``[max_parallelism]`` HBM-residency mask, state.tiers.*): cold-
    group lanes divert to the overflow ring inside wk.update. The mask
    is data, not structure — residency changes never recompile."""
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh

    def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid,
                   wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        state, activity, kgf = mask_update_shard(
            state, spec, kg_start[0], kg_end[0], hi, lo, ts, values,
            valid, wm[0], maxp, insert=insert, kg_fill=kg_fill,
            kg_res=rest[0] if tiered else None,
        )
        ovf_n = state.ovf_n
        return (
            jax.tree_util.tree_map(lambda x: x[None], state),
            ovf_n[None], activity[None], kgf[None],
        )

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(), P(), P(),
            P(SHARD_AXIS),
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS)),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def update_step(state, hi, lo, ts, values, valid, wm, *rest):
        """Returns (state', (ovf_n, activity, kg_fill)). The second
        element is a tiny NON-donated monitoring tuple: overflow-ring
        fill level, not-already-resident lane count, and per-key-group
        record counts of this batch ([n_shards, max_parallelism] — the
        traffic half of the skew telemetry; [n_shards, 0] when the
        builder's kg_fill flag is off). The host queues the handles
        and inspects them a few steps later — by then the values have
        materialized, so the read never stalls the step pipeline (lagged
        monitoring). `activity` drives the insert<->fast step tiering.
        """
        st, ovf_n, act, kgf = sharded(state, starts, ends, hi, lo, ts,
                                      values, valid, wm, *rest)
        return st, (ovf_n, act, kgf)

    update_step.tiered = tiered
    return update_step


def exchange_update_shard(state, spec: WindowStageSpec, kg_start, kg_end,
                          hi, lo, ts, values, valid, n: int, maxp: int,
                          cap: int, insert: bool = True, clear_rows=None,
                          kg_res=None):
    """Shared per-shard body: route this device's lane slice to owning
    shards over the mesh all_to_all, mask to owned key groups, and apply
    the window update. Used by the single-host exchange step and the
    cross-host DCN runner (runtime/dcn.py) so the shuffle semantics
    cannot diverge. Returns (state', activity) with bucket overflow
    already counted into dropped_capacity. (kg_fill telemetry stays a
    route-level concern here: the contract counts each record at its
    PRE-exchange source device, which update cannot see.)"""
    import dataclasses as _dc

    from flink_tpu.parallel.exchange import exchange_owned

    if spec.pre is not None:
        values, ts, valid = spec.pre(values, ts, valid)
    cols, r_hi, r_lo, mine, n_over = exchange_owned(
        {"ts": ts, "values": values}, hi, lo, valid, n, maxp, cap,
        kg_start, kg_end,
    )
    state, activity, _ = wk.update(state, spec.win, spec.red, r_hi, r_lo,
                                   cols["ts"], cols["values"], mine,
                                   insert=insert,
                                   direct=spec.layout == "direct",
                                   precombine=spec.precombine,
                                   clear_rows=clear_rows, kg_res=kg_res)
    state = _dc.replace(
        state, dropped_capacity=state.dropped_capacity + n_over
    )
    return state, activity


def build_window_update_step_exchange(ctx: MeshContext, spec: WindowStageSpec,
                                      batch_per_device: int,
                                      capacity_factor: float = 2.0,
                                      insert: bool = True,
                                      kg_fill: bool = False,
                                      tiered: bool = False):
    """Update step with a real ICI record exchange instead of
    replicate-and-mask: the host splits the batch over devices (each holds
    B/n lanes), each device buckets its lanes by owning shard and ONE
    jax.lax.all_to_all routes them (parallel/exchange.py). Per-device
    update work is O(B/n) — ingest throughput scales with chips, matching
    the reference's KeyGroupStreamPartitioner+RecordWriter shuffle
    (KeyGroupStreamPartitioner.java:53, RecordWriter.java:82).

    Bucket overflow (hash skew beyond capacity_factor x expected) is
    counted into dropped_capacity — surfaced, never silent."""
    import dataclasses as _dc

    from flink_tpu.parallel.exchange import bucket_capacity

    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    n = ctx.n_shards
    cap = bucket_capacity(batch_per_device, n, capacity_factor)

    def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid,
                   wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        state, activity = exchange_update_shard(
            state, spec, kg_start, kg_end, hi, lo, ts, values, valid,
            n, maxp, cap, insert=insert,
            kg_res=rest[0] if tiered else None,
        )
        state = _dc.replace(
            state, watermark=jnp.maximum(state.watermark, wm[0])
        )
        ovf_n = state.ovf_n
        # skew telemetry over THIS device's pre-exchange lane slice: each
        # record is counted once at its source device, so the host-side
        # shard sum equals the mask route's per-owner counts; compiled
        # out when the builder's kg_fill flag is off
        if kg_fill:
            kg_local = assign_to_key_group(
                route_hash(hi, lo, jnp), maxp, jnp
            )
            kgf = wk.kg_batch_fill(kg_local, valid, maxp)
        else:
            kgf = jnp.zeros(0, jnp.int32)
        return (
            jax.tree_util.tree_map(lambda x: x[None], state),
            ovf_n[None], activity[None], kgf[None],
        )

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            # batch arrays are SPLIT over devices on the batch axis
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(SHARD_AXIS),
            P(SHARD_AXIS),  # per-shard watermark
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS)),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def _jit_step(state, hi, lo, ts, values, valid, wm, *rest):
        st, ovf_n, act, kgf = sharded(state, starts, ends, hi, lo, ts,
                                      values, valid, wm, *rest)
        return st, (ovf_n, act, kgf)

    def update_step(state, hi, lo, ts, values, valid, wm, *rest):
        return _jit_step(state, hi, lo, ts, values, valid, wm, *rest)

    update_step.recv_lanes = n * cap
    update_step.bucket_cap = cap
    update_step.tiered = tiered
    # the jitted inner step, for AOT consumers (cost_analysis needs
    # .lower(), which the plain wrapper doesn't have)
    update_step.jit = _jit_step
    return update_step


def _fused_batch_stack(K: int, flat):
    """Stack the flat per-batch megastep operands back into [K, B] arrays.

    ``flat`` is (hi_0, lo_0, ticks_0, values_0, valid_0, hi_1, ...): K
    groups of 5. The stack happens INSIDE the jit so the executor can
    hand over K individually device-staged batches (the ingest ring
    stages them one poll at a time) without a host-side concat."""
    return [
        jnp.stack([flat[5 * i + j] for i in range(K)]) for j in range(5)
    ]


def build_window_megastep(ctx: MeshContext, spec: WindowStageSpec,
                          k_steps: int, insert: bool = True,
                          kg_fill: bool = False,
                          tiered: bool = False):
    """K-step dispatch fusion (pipeline.steps-per-dispatch): ONE jitted
    ``lax.scan`` applies a stack of K staged micro-batches against
    donated state in a single dispatch. Every fused group divides the
    fixed per-dispatch cost — Python ``run_update`` overhead, tracing,
    watchdog arming, and on a tunneled runtime the ~100ms dispatch round
    trip — by K, while the per-batch semantics (late checks against the
    pre-batch watermark, per-batch watermark advance) are byte-for-byte
    the sequential single steps': the scan body IS the single-step body.

    Signature: ``megastep(state, hi_0, lo_0, ticks_0, values_0, valid_0,
    ..., wmv)`` with wmv int32 [n_shards, K] (column i = batch i's
    watermark vector). Returns ``(state', (ovf_n, activity, kg_fill))``
    with the SAME monitoring shapes as the single step — ovf_n is the
    post-scan fill (monotone within a dispatch, so final == max),
    activity and kg_fill are summed over the K sub-steps — so the
    executor's lagged-monitoring consumer needs no fused-path variant.
    """
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    K = int(k_steps)

    def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid,
                   wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg_res = rest[0] if tiered else None   # scan-invariant, closed over

        def sub(st, xs):
            s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs
            st, act, kgf = mask_update_shard(
                st, spec, kg_start, kg_end, s_hi, s_lo, s_ts, s_vals,
                s_valid, s_wm, maxp, insert=insert, kg_fill=kg_fill,
                kg_res=kg_res,
            )
            return st, (act, kgf)

        state, (acts, kgfs) = jax.lax.scan(
            sub, state, (hi, lo, ts, values, valid, wm[0])
        )
        ovf_n = state.ovf_n
        act = jnp.sum(acts)
        kgf = kgfs.sum(axis=0) if kg_fill else jnp.zeros(0, jnp.int32)
        return (
            jax.tree_util.tree_map(lambda x: x[None], state),
            ovf_n[None], act[None], kgf[None],
        )

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(), P(), P(),   # [K, B] batch stacks, replicated
            P(SHARD_AXIS),             # wmv [n_shards, K]
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS)),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def megastep(state, *flat):
        if tiered:
            *batches, wmv, kg_res = flat
            tail = (wmv, kg_res)
        else:
            *batches, wmv = flat
            tail = (wmv,)
        stacks = _fused_batch_stack(K, batches)
        st, ovf_n, act, kgf = sharded(state, starts, ends, *stacks, *tail)
        return st, (ovf_n, act, kgf)

    megastep.k_steps = K
    megastep.tiered = tiered
    return megastep


def build_window_megastep_exchange(ctx: MeshContext, spec: WindowStageSpec,
                                   batch_per_device: int, k_steps: int,
                                   capacity_factor: float = 2.0,
                                   insert: bool = True,
                                   kg_fill: bool = False,
                                   tiered: bool = False):
    """Exchange-route megastep: the K-fused analog of
    build_window_update_step_exchange — each scan sub-step runs the
    shared ``exchange_update_shard`` body (bucket + all_to_all + masked
    update), so the fused shuffle semantics cannot diverge from the
    single-step route. Batch stacks arrive [K, B] SPLIT over devices on
    the batch (second) axis."""
    import dataclasses as _dc

    from flink_tpu.parallel.exchange import bucket_capacity

    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    n = ctx.n_shards
    cap = bucket_capacity(batch_per_device, n, capacity_factor)
    K = int(k_steps)

    def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid,
                   wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg_res = rest[0] if tiered else None

        def sub(st, xs):
            s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs
            st, act = exchange_update_shard(
                st, spec, kg_start, kg_end, s_hi, s_lo, s_ts, s_vals,
                s_valid, n, maxp, cap, insert=insert, kg_res=kg_res,
            )
            st = _dc.replace(st, watermark=jnp.maximum(st.watermark, s_wm))
            if kg_fill:
                kg_local = assign_to_key_group(
                    route_hash(s_hi, s_lo, jnp), maxp, jnp
                )
                kgf = wk.kg_batch_fill(kg_local, s_valid, maxp)
            else:
                kgf = jnp.zeros(0, jnp.int32)
            return st, (act, kgf)

        state, (acts, kgfs) = jax.lax.scan(
            sub, state, (hi, lo, ts, values, valid, wm[0])
        )
        ovf_n = state.ovf_n
        act = jnp.sum(acts)
        kgf = kgfs.sum(axis=0) if kg_fill else jnp.zeros(0, jnp.int32)
        return (
            jax.tree_util.tree_map(lambda x: x[None], state),
            ovf_n[None], act[None], kgf[None],
        )

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            # [K, B] stacks SPLIT over devices on the batch axis
            P(None, SHARD_AXIS), P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(SHARD_AXIS),
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS)),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def megastep(state, *flat):
        if tiered:
            *batches, wmv, kg_res = flat
            tail = (wmv, kg_res)
        else:
            *batches, wmv = flat
            tail = (wmv,)
        stacks = _fused_batch_stack(K, batches)
        st, ovf_n, act, kgf = sharded(state, starts, ends, *stacks, *tail)
        return st, (ovf_n, act, kgf)

    megastep.k_steps = K
    megastep.recv_lanes = n * cap
    megastep.bucket_cap = cap
    megastep.tiered = tiered
    return megastep


def build_window_megastep_fired(ctx: MeshContext, spec: WindowStageSpec,
                                k_steps: int, insert: bool = True,
                                kg_fill: bool = False,
                                reduced: bool = False,
                                tiered: bool = False):
    """Resident-pipeline megastep (pipeline.fused-fire, ISSUE 7): the
    K-fused ``lax.scan`` with the FIRE SWEEP folded into the scan body.
    Each sub-step applies its micro-batch (the shared mask_update_shard
    body, so the routing semantics cannot diverge from the single step)
    and then runs ``wk.advance_and_fire_resident`` against its own
    watermark: a pane-boundary crossing inside the K-group fires WITHIN
    the scan instead of breaking the group into single dispatches plus a
    separate fire dispatch (the split path this replaces serialized
    update and fire at every boundary).

    The per-sub-step advance is affordable because the fire evaluation
    is lax.cond-gated on "anything due" and the purge plane-clears
    DEFER into the next sub-step's ring-reset sweep (carried ``pending``
    rows; ``apply_pending_purge`` reconciles after the scan so the
    returned state is bit-identical to the sequential interleaving).

    Returns ``(state', (ovf_n, activity, kg_fill), fires)`` where
    ``fires`` is a CompactFires pytree with a leading [n_shards, K] axis
    — sub-step i's payload under sub-step i's watermark. The executor
    consumes the handles LAGGED (runtime/executor.py consume_fires), so
    surfacing fires costs no step-loop sync.

    ``reduced=True`` surfaces ReducedFires instead — per-lane scalars,
    no payload planes. The scan stacks a payload slot for every
    sub-step whether it fired or not, so device_reduce sink topologies
    (which never read payloads) skip the [K, F, C] zero traffic that
    otherwise dominates the resident overhead on quiet streams."""
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    K = int(k_steps)

    def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid,
                   wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg_res = rest[0] if tiered else None
        pend0 = jnp.zeros(spec.win.ring, bool)

        def sub(carry, xs):
            st, pend = carry
            s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs
            st, act, kgf = mask_update_shard(
                st, spec, kg_start, kg_end, s_hi, s_lo, s_ts, s_vals,
                s_valid, s_wm, maxp, insert=insert, kg_fill=kg_fill,
                clear_rows=pend, kg_res=kg_res,
            )
            st, pend, cf = wk.advance_and_fire_resident(
                st, spec.win, spec.red, s_wm, reduced=reduced
            )
            return (st, pend), (act, kgf, cf)

        (state, pend), (acts, kgfs, fires) = jax.lax.scan(
            sub, (state, pend0), (hi, lo, ts, values, valid, wm[0])
        )
        state = wk.apply_pending_purge(state, spec.win, spec.red, pend)
        ovf_n = state.ovf_n
        act = jnp.sum(acts)
        kgf = kgfs.sum(axis=0) if kg_fill else jnp.zeros(0, jnp.int32)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        return (
            pack(state), ovf_n[None], act[None], kgf[None], pack(fires),
        )

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(), P(), P(),   # [K, B] batch stacks, replicated
            P(SHARD_AXIS),             # wmv [n_shards, K]
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def megastep(state, *flat):
        if tiered:
            *batches, wmv, kg_res = flat
            tail = (wmv, kg_res)
        else:
            *batches, wmv = flat
            tail = (wmv,)
        stacks = _fused_batch_stack(K, batches)
        st, ovf_n, act, kgf, fires = sharded(
            state, starts, ends, *stacks, *tail
        )
        return st, (ovf_n, act, kgf), fires

    megastep.k_steps = K
    megastep.fused_fire = True
    megastep.fused_fire_reduced = reduced
    megastep.tiered = tiered
    return megastep


def build_window_megastep_fired_exchange(ctx: MeshContext,
                                         spec: WindowStageSpec,
                                         batch_per_device: int,
                                         k_steps: int,
                                         capacity_factor: float = 2.0,
                                         insert: bool = True,
                                         kg_fill: bool = False,
                                         reduced: bool = False,
                                         tiered: bool = False):
    """Exchange-route resident megastep: the fused-fire analog of
    build_window_megastep_exchange — each scan sub-step runs the shared
    ``exchange_update_shard`` body (bucket + all_to_all + masked update)
    followed by the gated resident advance, so neither the shuffle nor
    the fire semantics can diverge from the split-dispatch route. Batch
    stacks arrive [K, B] SPLIT over devices on the batch (second) axis;
    fires come back per shard like the mask variant."""
    import dataclasses as _dc

    from flink_tpu.parallel.exchange import bucket_capacity

    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    n = ctx.n_shards
    cap = bucket_capacity(batch_per_device, n, capacity_factor)
    K = int(k_steps)

    def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid,
                   wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg_res = rest[0] if tiered else None
        pend0 = jnp.zeros(spec.win.ring, bool)

        def sub(carry, xs):
            st, pend = carry
            s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs
            st, act = exchange_update_shard(
                st, spec, kg_start, kg_end, s_hi, s_lo, s_ts, s_vals,
                s_valid, n, maxp, cap, insert=insert, clear_rows=pend,
                kg_res=kg_res,
            )
            st = _dc.replace(st, watermark=jnp.maximum(st.watermark, s_wm))
            if kg_fill:
                kg_local = assign_to_key_group(
                    route_hash(s_hi, s_lo, jnp), maxp, jnp
                )
                kgf = wk.kg_batch_fill(kg_local, s_valid, maxp)
            else:
                kgf = jnp.zeros(0, jnp.int32)
            st, pend, cf = wk.advance_and_fire_resident(
                st, spec.win, spec.red, s_wm, reduced=reduced
            )
            return (st, pend), (act, kgf, cf)

        (state, pend), (acts, kgfs, fires) = jax.lax.scan(
            sub, (state, pend0), (hi, lo, ts, values, valid, wm[0])
        )
        state = wk.apply_pending_purge(state, spec.win, spec.red, pend)
        ovf_n = state.ovf_n
        act = jnp.sum(acts)
        kgf = kgfs.sum(axis=0) if kg_fill else jnp.zeros(0, jnp.int32)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        return (
            pack(state), ovf_n[None], act[None], kgf[None], pack(fires),
        )

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            # [K, B] stacks SPLIT over devices on the batch axis
            P(None, SHARD_AXIS), P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(SHARD_AXIS),
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def megastep(state, *flat):
        if tiered:
            *batches, wmv, kg_res = flat
            tail = (wmv, kg_res)
        else:
            *batches, wmv = flat
            tail = (wmv,)
        stacks = _fused_batch_stack(K, batches)
        st, ovf_n, act, kgf, fires = sharded(
            state, starts, ends, *stacks, *tail
        )
        return st, (ovf_n, act, kgf), fires

    megastep.k_steps = K
    megastep.fused_fire = True
    megastep.fused_fire_reduced = reduced
    megastep.recv_lanes = n * cap
    megastep.bucket_cap = cap
    megastep.tiered = tiered
    return megastep


def _zero_slot_fires(spec: WindowStageSpec, reduced: bool):
    """Zero-shaped per-sub-step fire payload for the resident drain's
    skip branch: field-for-field the shapes/dtypes a live sub-step's
    ``wk.advance_and_fire_resident`` emits (its own internal skip branch
    packs the same zeros), so both ``lax.cond`` branches of the drain
    body stack identically and an unconsumed ring slot is bit-identical
    to packing an empty fire — the executor's lagged consume_fires sees
    counts == 0 and emits nothing."""
    F = spec.win.fires_per_step
    C = spec.capacity_per_shard
    zi = jnp.zeros(F, jnp.int32)
    zf = jnp.zeros(F, jnp.float32)
    zb = jnp.zeros(F, bool)
    n0 = jnp.zeros((), jnp.int32)
    if reduced:
        return wk.ReducedFires(zi, zi, n0, zb, zf)
    return wk.CompactFires(
        jnp.zeros((F, C), jnp.uint32),
        jnp.zeros((F, C), jnp.uint32),
        jnp.zeros((F, C) + spec.red.out_shape, spec.red.out_dtype),
        zi, zi, n0, zb, zf,
    )


# per-slot drain-interior counters (observability.drain-stats, ISSUE 14):
# index order of the int32 stats vector each live drain slot emits. The
# scan stacks them [D, N]; shard_map packs [n_shards, D, N] — the
# "flight recorder" payload the executor unpacks LAGGED alongside fires.
# The tuple lives with the host-side unpacker so packer and unpacker
# cannot drift (flink_tpu/metrics/drain_stats.py documents each field).
from flink_tpu.metrics.drain_stats import (  # noqa: E402
    DRAIN_STAT_FIELDS, STAGE_STAT_FIELDS,
)


def _slot_drain_stats(st, spec: WindowStageSpec, s_valid, act, kgf, cf,
                      wm_before, late0, cap0, defer_fires=False):
    """One live slot's DRAIN_STAT_FIELDS vector — element ops and tiny
    reductions over fields the fused body already materialized, so the
    telemetry-ON kernels add zero sort/scatter/gather passes (the
    op-budget ledger pins the OFF variants byte-identical).

    ``defer_fires`` zeroes the two fire-plane reductions (fire_lanes,
    fired_keys): in the CHAINED drain the per-slot CompactFires are
    stacked for the stage tail rather than consumed in the slot body,
    and reducing them inside the scan forces XLA to materialize the
    fire pack twice per slot (~25% on the chained body). The builder
    fills the columns after the scan with one vectorized pass over the
    stacked fires (_deferred_fire_columns) — same numbers, one read."""
    slide = jnp.int32(spec.win.slide_ticks)
    # clamp the pre-advance watermark so a fresh job's MIN sentinel
    # cannot overflow the int32 pane subtraction, and report the very
    # first advance (no meaningful baseline) as zero panes crossed
    wb = jnp.maximum(wm_before, st.watermark - jnp.int32(1 << 20))
    panes = jnp.maximum(
        jnp.int32(0), st.watermark // slide - wb // slide
    )
    panes = jnp.where(
        wm_before < jnp.int32(-(2 ** 30)), jnp.int32(0), panes
    )
    kg_max = (
        jnp.max(kgf) if kgf.shape[0] else jnp.zeros((), jnp.int32)
    )
    zero = jnp.zeros((), jnp.int32)
    return jnp.stack([
        jnp.sum(s_valid, dtype=jnp.int32),
        act,
        zero if defer_fires else jnp.sum(cf.lane_valid, dtype=jnp.int32),
        zero if defer_fires else jnp.sum(cf.counts, dtype=jnp.int32),
        st.dropped_late - late0,
        st.dropped_capacity - cap0,
        st.ovf_n,
        kg_max,
        panes,
    ])


def _deferred_fire_columns(ds_stack, cf_stack):
    """Fill the deferred fire_lanes / fired_keys columns of a [D, N]
    per-slot stats stack from the scan's STACKED CompactFires — one
    vectorized reduction per drain instead of one per slot inside the
    scan (see _slot_drain_stats defer_fires). Skip slots stacked zero
    fires, so their columns stay zero exactly as the inline path."""
    lv, cnt = cf_stack.lane_valid, cf_stack.counts
    fire_lanes = jnp.sum(
        lv, dtype=jnp.int32, axis=tuple(range(1, lv.ndim))
    )
    fired_keys = jnp.sum(
        cnt, dtype=jnp.int32, axis=tuple(range(1, cnt.ndim))
    )
    return jnp.concatenate([
        ds_stack[:, :2], fire_lanes[:, None], fired_keys[:, None],
        ds_stack[:, 4:],
    ], axis=1)


def build_window_resident_drain(ctx: MeshContext, spec: WindowStageSpec,
                                depth: int, insert: bool = True,
                                kg_fill: bool = False,
                                reduced: bool = False,
                                drain_stats: bool = False,
                                tiered: bool = False):
    """Device-resident ring-drain loop (pipeline.resident-loop, ISSUE
    12): ONE jitted dispatch consumes up to ``depth`` staged ring slots
    against donated state, running the PR 7 fused update+fire body per
    slot — steady state costs one host round trip per ring DRAIN instead
    of one per megastep.

    Lowering choice (the ISSUE allows ``lax.while_loop`` or a long-K
    scan with a read-only early-exit cond): a fixed-depth ``lax.scan``
    whose body is gated by ``lax.cond(i < count, live, skip)``. The scan
    stacks the per-slot fire payloads for free (the while_loop form
    needs a dynamic_update_slice per payload field per iteration — more
    ops under the PR 10 op-budget ledger and a worse scatter count), the
    carry threading is identical to the proven megastep_fired scan, and
    XLA's conditional executes only the taken branch, so slots past the
    write cursor cost the scalar predicate, not an update pass. ``count``
    is a TRACED int32 operand — one compile per (route, tier) serves
    every fill level, so the loop never recompiles as ring occupancy
    varies (the compile-signature ledger pins this).

    The host-side exit conditions (ring-empty, fire-buffer high water,
    monitoring cadence, checkpoint-cut request) all resolve to the
    ``count`` the executor passes: it caps the drain at whichever
    boundary comes first, and slots past the cut stay in the ring for
    the next drain — the exactly-once cut is the ring-drain boundary.

    Signature: ``drain(state, hi_0, lo_0, ticks_0, values_0, valid_0,
    ..., wmv, count)`` — ``depth`` staged batch 5-tuples (slots past
    ``count`` repeat an already-staged slot; the skip branch never reads
    them), wmv int32 [n_shards, depth] (sentinel past count), count
    int32 scalar. Returns ``(state', (ovf_n, activity, kg_fill),
    fires)`` with fires stacked [n_shards, depth] exactly like
    ``build_window_megastep_fired`` at K=depth, so the executor's lagged
    fire consumption and monitoring paths need no drain-specific
    variant. With ``drain_stats`` (observability.drain-stats) a fourth
    return element rides along: an int32 [n_shards, depth,
    len(DRAIN_STAT_FIELDS)] per-slot flight-recorder stack, consumed
    lagged with the fires; off, the kernel and its return contract are
    byte-identical to pre-telemetry (the op-budget ledger asserts it)."""
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    D = int(depth)

    def shard_body(state, kg_start, kg_end, count, hi, lo, ts, values,
                   valid, wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg_res = rest[0] if tiered else None   # scan-invariant residency
        pend0 = jnp.zeros(spec.win.ring, bool)

        def sub(carry, xs):
            i, s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs

            def live(op):
                st, pend = op
                wm_b = st.watermark
                late0, cap0 = st.dropped_late, st.dropped_capacity
                st, act, kgf = mask_update_shard(
                    st, spec, kg_start, kg_end, s_hi, s_lo, s_ts,
                    s_vals, s_valid, s_wm, maxp, insert=insert,
                    kg_fill=kg_fill, clear_rows=pend, kg_res=kg_res,
                )
                st, pend, cf = wk.advance_and_fire_resident(
                    st, spec.win, spec.red, s_wm, reduced=reduced
                )
                if drain_stats:
                    ds = _slot_drain_stats(st, spec, s_valid, act, kgf,
                                           cf, wm_b, late0, cap0)
                    return (st, pend), (act, kgf, cf, ds)
                return (st, pend), (act, kgf, cf)

            def skip(op):
                kgf = jnp.zeros(maxp if kg_fill else 0, jnp.int32)
                ys = (jnp.zeros((), jnp.int32), kgf,
                      _zero_slot_fires(spec, reduced))
                if drain_stats:
                    ys += (jnp.zeros(len(DRAIN_STAT_FIELDS), jnp.int32),)
                return op, ys

            return jax.lax.cond(i < count, live, skip, carry)

        (state, pend), ys = jax.lax.scan(
            sub, (state, pend0),
            (jnp.arange(D, dtype=jnp.int32), hi, lo, ts, values, valid,
             wm[0]),
        )
        acts, kgfs, fires = ys[:3]
        state = wk.apply_pending_purge(state, spec.win, spec.red, pend)
        ovf_n = state.ovf_n
        act = jnp.sum(acts)
        kgf = kgfs.sum(axis=0) if kg_fill else jnp.zeros(0, jnp.int32)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        out = (
            pack(state), ovf_n[None], act[None], kgf[None], pack(fires),
        )
        if drain_stats:
            out += (ys[3][None],)      # [1, D, N] flight-recorder stack
        return out

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(),                       # count: replicated scalar cursor
            P(), P(), P(), P(), P(),   # [D, B] batch stacks, replicated
            P(SHARD_AXIS),             # wmv [n_shards, D]
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS))
        + ((P(SHARD_AXIS),) if drain_stats else ()),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def drain(state, *flat):
        if tiered:
            *batches, wmv, count, kg_res = flat
            tail = (wmv, kg_res)
        else:
            *batches, wmv, count = flat
            tail = (wmv,)
        stacks = _fused_batch_stack(D, batches)
        res = sharded(
            state, starts, ends, jnp.asarray(count, jnp.int32),
            *stacks, *tail,
        )
        st, ovf_n, act, kgf, fires = res[:5]
        if drain_stats:
            return st, (ovf_n, act, kgf), fires, res[5]
        return st, (ovf_n, act, kgf), fires

    drain.k_steps = D
    drain.ring_depth = D
    drain.resident_drain = True
    drain.fused_fire = True
    drain.fused_fire_reduced = reduced
    drain.drain_stats = drain_stats
    drain.tiered = tiered
    return drain


def build_window_resident_drain_exchange(ctx: MeshContext,
                                         spec: WindowStageSpec,
                                         batch_per_device: int,
                                         depth: int,
                                         capacity_factor: float = 2.0,
                                         insert: bool = True,
                                         kg_fill: bool = False,
                                         reduced: bool = False,
                                         drain_stats: bool = False,
                                         tiered: bool = False):
    """Exchange-route resident drain: the ring-drain analog of
    build_window_megastep_fired_exchange — each live slot runs the
    shared ``exchange_update_shard`` body (bucket + all_to_all + masked
    update) followed by the gated resident advance, under the same
    ``lax.cond(i < count)`` gate as the mask-route drain, so neither the
    shuffle nor the fire semantics can diverge between routes or fill
    levels. Batch stacks arrive [D, B] SPLIT over devices on the batch
    (second) axis; ``count`` is replicated. Note the all_to_all runs
    only in the live branch: every device takes the same branch because
    ``count`` is replicated, so the collective stays globally
    consistent."""
    import dataclasses as _dc

    from flink_tpu.parallel.exchange import bucket_capacity

    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    n = ctx.n_shards
    cap = bucket_capacity(batch_per_device, n, capacity_factor)
    D = int(depth)

    def shard_body(state, kg_start, kg_end, count, hi, lo, ts, values,
                   valid, wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg_res = rest[0] if tiered else None
        pend0 = jnp.zeros(spec.win.ring, bool)

        def sub(carry, xs):
            i, s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs

            def live(op):
                st, pend = op
                wm_b = st.watermark
                late0, cap0 = st.dropped_late, st.dropped_capacity
                st, act = exchange_update_shard(
                    st, spec, kg_start, kg_end, s_hi, s_lo, s_ts,
                    s_vals, s_valid, n, maxp, cap, insert=insert,
                    clear_rows=pend, kg_res=kg_res,
                )
                st = _dc.replace(
                    st, watermark=jnp.maximum(st.watermark, s_wm)
                )
                if kg_fill:
                    kg_local = assign_to_key_group(
                        route_hash(s_hi, s_lo, jnp), maxp, jnp
                    )
                    kgf = wk.kg_batch_fill(kg_local, s_valid, maxp)
                else:
                    kgf = jnp.zeros(0, jnp.int32)
                st, pend, cf = wk.advance_and_fire_resident(
                    st, spec.win, spec.red, s_wm, reduced=reduced
                )
                if drain_stats:
                    ds = _slot_drain_stats(st, spec, s_valid, act, kgf,
                                           cf, wm_b, late0, cap0)
                    return (st, pend), (act, kgf, cf, ds)
                return (st, pend), (act, kgf, cf)

            def skip(op):
                kgf = jnp.zeros(maxp if kg_fill else 0, jnp.int32)
                ys = (jnp.zeros((), jnp.int32), kgf,
                      _zero_slot_fires(spec, reduced))
                if drain_stats:
                    ys += (jnp.zeros(len(DRAIN_STAT_FIELDS), jnp.int32),)
                return op, ys

            return jax.lax.cond(i < count, live, skip, carry)

        (state, pend), ys = jax.lax.scan(
            sub, (state, pend0),
            (jnp.arange(D, dtype=jnp.int32), hi, lo, ts, values, valid,
             wm[0]),
        )
        acts, kgfs, fires = ys[:3]
        state = wk.apply_pending_purge(state, spec.win, spec.red, pend)
        ovf_n = state.ovf_n
        act = jnp.sum(acts)
        kgf = kgfs.sum(axis=0) if kg_fill else jnp.zeros(0, jnp.int32)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        out = (
            pack(state), ovf_n[None], act[None], kgf[None], pack(fires),
        )
        if drain_stats:
            out += (ys[3][None],)
        return out

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(),                       # count: replicated scalar cursor
            # [D, B] stacks SPLIT over devices on the batch axis
            P(None, SHARD_AXIS), P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(SHARD_AXIS),
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS))
        + ((P(SHARD_AXIS),) if drain_stats else ()),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def drain(state, *flat):
        if tiered:
            *batches, wmv, count, kg_res = flat
            tail = (wmv, kg_res)
        else:
            *batches, wmv, count = flat
            tail = (wmv,)
        stacks = _fused_batch_stack(D, batches)
        res = sharded(
            state, starts, ends, jnp.asarray(count, jnp.int32),
            *stacks, *tail,
        )
        st, ovf_n, act, kgf, fires = res[:5]
        if drain_stats:
            return st, (ovf_n, act, kgf), fires, res[5]
        return st, (ovf_n, act, kgf), fires

    drain.k_steps = D
    drain.ring_depth = D
    drain.resident_drain = True
    drain.fused_fire = True
    drain.fused_fire_reduced = reduced
    drain.recv_lanes = n * cap
    drain.bucket_cap = cap
    drain.drain_stats = drain_stats
    drain.tiered = tiered
    return drain


def build_window_sharded_drain(ctx: MeshContext, spec: WindowStageSpec,
                               depth: int, insert: bool = True,
                               kg_fill: bool = False,
                               reduced: bool = False,
                               drain_stats: bool = False,
                               tiered: bool = False):
    """Data-parallel resident drain (pipeline.data-parallel, ISSUE 13):
    the ring-drain scan lowered shard-LOCALLY — the ingest side already
    partitioned each batch by owning key-group slice and published the
    per-shard lane slices into the owning shard's ring slot, so the
    keyed body here is mask_update_shard over lanes that are ALL local
    by construction. Zero cross-chip collectives on the hot path: no
    all_to_all (records arrived pre-routed), no replicated full-batch
    broadcast (each chip touches only its own cap lanes, O(cap) work
    per chip instead of the mask route's O(B)).

    The per-shard independence is what buys the third delta: ``counts``
    is an int32 [n_shards] VECTOR under P(SHARD_AXIS), so each shard
    gates its scan on its OWN fill level. The exchange drain must keep
    ``count`` replicated (its all_to_all would deadlock if shards took
    different branches); with no collective in this body, divergent
    counts are safe — one slow shard's shallow ring never forces the
    others to under-drain.

    Signature: ``drain(state, hi_0, lo_0, ticks_0, values_0, valid_0,
    ..., wmv, counts)`` — ``depth`` staged 5-tuples of [n_shards, cap]
    arrays split over devices on the LEADING axis (slots past a shard's
    count repeat stale lanes; the skip branch never reads them), wmv
    int32 [n_shards, depth], counts int32 [n_shards]. Returns the same
    ``(state', (ovf_n, activity, kg_fill), fires)`` contract as
    build_window_resident_drain, fires stacked [n_shards, depth] — the
    executor's lagged consume_fires merges the per-shard packs host-
    side unchanged."""
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    D = int(depth)

    def shard_body(state, kg_start, kg_end, counts, hi, lo, ts, values,
                   valid, wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg_res = rest[0] if tiered else None
        count = counts[0]          # this shard's OWN fill level
        pend0 = jnp.zeros(spec.win.ring, bool)

        def sub(carry, xs):
            i, s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs

            def live(op):
                st, pend = op
                wm_b = st.watermark
                late0, cap0 = st.dropped_late, st.dropped_capacity
                st, act, kgf = mask_update_shard(
                    st, spec, kg_start, kg_end, s_hi, s_lo, s_ts,
                    s_vals, s_valid, s_wm, maxp, insert=insert,
                    kg_fill=kg_fill, clear_rows=pend, kg_res=kg_res,
                )
                st, pend, cf = wk.advance_and_fire_resident(
                    st, spec.win, spec.red, s_wm, reduced=reduced
                )
                if drain_stats:
                    ds = _slot_drain_stats(st, spec, s_valid, act, kgf,
                                           cf, wm_b, late0, cap0)
                    return (st, pend), (act, kgf, cf, ds)
                return (st, pend), (act, kgf, cf)

            def skip(op):
                kgf = jnp.zeros(maxp if kg_fill else 0, jnp.int32)
                ys = (jnp.zeros((), jnp.int32), kgf,
                      _zero_slot_fires(spec, reduced))
                if drain_stats:
                    ys += (jnp.zeros(len(DRAIN_STAT_FIELDS), jnp.int32),)
                return op, ys

            return jax.lax.cond(i < count, live, skip, carry)

        (state, pend), ys = jax.lax.scan(
            sub, (state, pend0),
            # [D, 1, cap] per-shard batch stacks squeeze the split axis
            (jnp.arange(D, dtype=jnp.int32), hi[:, 0], lo[:, 0],
             ts[:, 0], values[:, 0], valid[:, 0], wm[0]),
        )
        acts, kgfs, fires = ys[:3]
        state = wk.apply_pending_purge(state, spec.win, spec.red, pend)
        ovf_n = state.ovf_n
        act = jnp.sum(acts)
        kgf = kgfs.sum(axis=0) if kg_fill else jnp.zeros(0, jnp.int32)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        out = (
            pack(state), ovf_n[None], act[None], kgf[None], pack(fires),
        )
        if drain_stats:
            out += (ys[3][None],)
        return out

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(SHARD_AXIS),             # counts: per-shard fill levels
            # [D, n_shards, cap] stacks SPLIT on the shard axis: each
            # chip receives only its own pre-routed lane slices
            P(None, SHARD_AXIS), P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(SHARD_AXIS),             # wmv [n_shards, D]
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS))
        + ((P(SHARD_AXIS),) if drain_stats else ()),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def drain(state, *flat):
        if tiered:
            *batches, wmv, counts, kg_res = flat
            tail = (wmv, kg_res)
        else:
            *batches, wmv, counts = flat
            tail = (wmv,)
        stacks = _fused_batch_stack(D, batches)
        res = sharded(
            state, starts, ends, jnp.asarray(counts, jnp.int32),
            *stacks, *tail,
        )
        st, ovf_n, act, kgf, fires = res[:5]
        if drain_stats:
            return st, (ovf_n, act, kgf), fires, res[5]
        return st, (ovf_n, act, kgf), fires

    drain.k_steps = D
    drain.ring_depth = D
    drain.resident_drain = True
    drain.sharded_drain = True
    drain.fused_fire = True
    drain.fused_fire_reduced = reduced
    drain.drain_stats = drain_stats
    drain.tiered = tiered
    return drain


def _zero_fires_stack(spec: WindowStageSpec, reduced: bool, depth: int):
    """[depth]-stacked zero fire payload — the while-drain's accumulation
    buffer. Row ``i`` is written by dynamic_update_index_in_dim when slot
    ``i`` retires; unconsumed rows stay bit-identical to the scan drain's
    skip-branch zeros, so the executor's lagged consume_fires treats both
    lowering forms identically."""
    z = _zero_slot_fires(spec, reduced)
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros((depth,) + x.shape, x.dtype), z
    )


def _while_drain_limit(cursor, base, staged, max_slots):
    """The live trip bound of one while-drain dispatch: slots the publish
    cursor has committed past this drain's base, clamped to what the host
    actually staged into the operand stacks and the configured per-
    dispatch bound. Re-evaluated in the loop CONDITION each iteration so
    a cursor store landing mid-drain (the DeviceBatchRing's HBM cursor
    slot, donated alongside the payloads on an aliasing runtime) extends
    the trip count of the dispatch already in flight."""
    return jnp.minimum(
        jnp.minimum(
            jnp.maximum(cursor - base, jnp.int32(0)), staged
        ),
        jnp.int32(max_slots),
    )


def build_window_while_drain(ctx: MeshContext, spec: WindowStageSpec,
                             max_slots: int, insert: bool = True,
                             kg_fill: bool = False,
                             reduced: bool = False,
                             drain_stats: bool = False,
                             tiered: bool = False):
    """Early-exit live ring drain (pipeline.resident-loop=while, ISSUE
    20): the resident drain lowered as a ``lax.while_loop`` whose
    condition re-reads a device-visible PUBLISH CURSOR instead of a
    host-frozen count — a batch the ingest thread commits while the
    drain is running is retired *inside the same dispatch*, so the
    structural one-dispatch-per-publish-burst cost of the count-gated
    scan disappears under sustained ingest.

    Contract vs the scan drain (build_window_resident_drain):

    * the ``count`` operand is replaced by ``(cursor, base, staged)`` —
      cursor int32 [1] is the ring's device cursor slot (absolute
      publish seq, stored by the ingest thread after each commit; the
      executor donates it so an aliasing runtime lets the in-flight
      loop observe the store), base is the drain group's first ring
      seq, staged is how many slot payloads the host bound into THIS
      dispatch's operand stacks. The trip bound is
      ``clamp(cursor - base, 0, min(staged, max_slots))``: on a
      runtime without host->HBM stores into dispatched buffers the
      cursor term freezes at its dispatch-time value and the kernel
      degrades exactly to the scan drain's count gating — never reads
      a slot the host didn't stage.
    * ``max_slots`` (pipeline.while-drain.max-slots) bounds ONE
      dispatch, so the exactly-once cut, the watchdog deadline
      (``Watchdog.arm`` scale = the bound) and the flight-recorder
      payload ([n_shards, max_slots, N] with zeroed dead rows) stay
      well-defined however long the publisher keeps the cursor ahead.
    * a fourth return element, ``consumed`` int32 [1], reports the live
      slot count this dispatch actually retired — the host's release /
      telemetry boundary (it matches the cursor slot's shape+dtype, so
      the donated cursor buffer is reused for it).

    Fires cannot ride a scan stack here: each retired slot's payload is
    written into a preallocated [max_slots, ...] buffer with one
    dynamic_update_slice per field per iteration — a deliberately
    different op profile from the scan drain, pinned by its own
    ``step.while_drain.*`` op-budget/signature ledger entries."""
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    D = int(max_slots)
    n_ds = len(DRAIN_STAT_FIELDS)

    def shard_body(state, kg_start, kg_end, cursor, base, staged, hi,
                   lo, ts, values, valid, wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg_res = rest[0] if tiered else None
        wm_l = wm[0]                       # [D] per-shard watermarks
        pend0 = jnp.zeros(spec.win.ring, bool)
        kgf0 = jnp.zeros(maxp if kg_fill else 0, jnp.int32)
        fires0 = _zero_fires_stack(spec, reduced, D)
        ds0 = jnp.zeros((D, n_ds), jnp.int32)

        def cond(carry):
            i, cur = carry[0], carry[1]
            # the live re-read: cur is carried so the bound check sits
            # INSIDE the loop, not hoisted as a dispatch-time constant
            return i < _while_drain_limit(cur[0], base, staged, D)

        def body(carry):
            i, cur, st, pend, act, kgf, fires, ds = carry
            pick = lambda a: jax.lax.dynamic_index_in_dim(
                a, i, keepdims=False
            )
            s_hi, s_lo, s_ts = pick(hi), pick(lo), pick(ts)
            s_vals, s_valid, s_wm = pick(values), pick(valid), pick(wm_l)
            wm_b = st.watermark
            late0, cap0 = st.dropped_late, st.dropped_capacity
            st, a, kg = mask_update_shard(
                st, spec, kg_start, kg_end, s_hi, s_lo, s_ts, s_vals,
                s_valid, s_wm, maxp, insert=insert, kg_fill=kg_fill,
                clear_rows=pend, kg_res=kg_res,
            )
            st, pend, cf = wk.advance_and_fire_resident(
                st, spec.win, spec.red, s_wm, reduced=reduced
            )
            fires = jax.tree_util.tree_map(
                lambda buf, v: jax.lax.dynamic_update_index_in_dim(
                    buf, v, i, 0
                ),
                fires, cf,
            )
            if drain_stats:
                row = _slot_drain_stats(st, spec, s_valid, a, kg, cf,
                                        wm_b, late0, cap0)
                ds = jax.lax.dynamic_update_index_in_dim(ds, row, i, 0)
            return (i + 1, cur, st, pend, act + a,
                    kgf + kg if kg_fill else kgf, fires, ds)

        i_fin, _cur, state, pend, act, kgf, fires, ds = \
            jax.lax.while_loop(
                cond, body,
                (jnp.int32(0), cursor, state, pend0,
                 jnp.zeros((), jnp.int32), kgf0, fires0, ds0),
            )
        state = wk.apply_pending_purge(state, spec.win, spec.red, pend)
        ovf_n = state.ovf_n
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        out = (
            pack(state), ovf_n[None], act[None], kgf[None], pack(fires),
            i_fin[None],               # consumed: live retired-slot count
        )
        if drain_stats:
            out += (ds[None],)         # [1, max_slots, N] recorder stack
        return out

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(),             # cursor [1], base, staged: all
            #                            replicated so every shard takes
            #                            the same trip count
            P(), P(), P(), P(), P(),   # [D, B] batch stacks, replicated
            P(SHARD_AXIS),             # wmv [n_shards, D]
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS), P())
        + ((P(SHARD_AXIS),) if drain_stats else ()),
        check_vma=False,
    )

    # donate the state AND the cursor slot: consumed [1] int32 reuses the
    # cursor's buffer, and on an aliasing runtime the donation is what
    # lets the ingest thread's commit store land in the dispatched slot
    @partial(jax.jit, donate_argnums=(0, 5 * D + 2))
    def drain(state, *flat):
        if tiered:
            *batches, wmv, cursor, base, staged, kg_res = flat
            tail = (kg_res,)
        else:
            *batches, wmv, cursor, base, staged = flat
            tail = ()
        stacks = _fused_batch_stack(D, batches)
        res = sharded(
            state, starts, ends, jnp.asarray(cursor, jnp.int32),
            jnp.asarray(base, jnp.int32), jnp.asarray(staged, jnp.int32),
            *stacks, wmv, *tail,
        )
        st, ovf_n, act, kgf, fires, consumed = res[:6]
        if drain_stats:
            return st, (ovf_n, act, kgf), fires, consumed, res[6]
        return st, (ovf_n, act, kgf), fires, consumed

    drain.k_steps = D
    drain.ring_depth = D
    drain.max_slots = D
    drain.resident_drain = True
    drain.while_drain = True
    drain.fused_fire = True
    drain.fused_fire_reduced = reduced
    drain.drain_stats = drain_stats
    drain.tiered = tiered
    return drain


def build_window_while_drain_sharded(ctx: MeshContext,
                                     spec: WindowStageSpec,
                                     max_slots: int, insert: bool = True,
                                     kg_fill: bool = False,
                                     reduced: bool = False,
                                     drain_stats: bool = False,
                                     tiered: bool = False):
    """Data-parallel early-exit drain: build_window_while_drain lowered
    shard-LOCALLY over pre-routed per-shard lane slices (the
    build_window_sharded_drain layout). ``cursor``/``base``/``staged``
    are int32 [n_shards] VECTORS under P(SHARD_AXIS): each shard's
    while_loop trips on its OWN publish cursor, and — with zero
    collectives in the keyed body — divergent trip counts are safe, so
    one shard's quiet ring never under-drains a hot one mid-dispatch.
    ``consumed`` returns [n_shards]: each shard's live retired count,
    the per-shard release boundary (and the donated cursor vector's
    buffer)."""
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    D = int(max_slots)
    n_ds = len(DRAIN_STAT_FIELDS)

    def shard_body(state, kg_start, kg_end, cursor, base, staged, hi,
                   lo, ts, values, valid, wm, *rest):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg_res = rest[0] if tiered else None
        s_base, s_staged = base[0], staged[0]
        # [D, 1, cap] per-shard batch stacks squeeze the split axis
        b_hi, b_lo, b_ts = hi[:, 0], lo[:, 0], ts[:, 0]
        b_vals, b_valid = values[:, 0], valid[:, 0]
        wm_l = wm[0]
        pend0 = jnp.zeros(spec.win.ring, bool)
        kgf0 = jnp.zeros(maxp if kg_fill else 0, jnp.int32)
        fires0 = _zero_fires_stack(spec, reduced, D)
        ds0 = jnp.zeros((D, n_ds), jnp.int32)

        def cond(carry):
            i, cur = carry[0], carry[1]
            return i < _while_drain_limit(cur[0], s_base, s_staged, D)

        def body(carry):
            i, cur, st, pend, act, kgf, fires, ds = carry
            pick = lambda a: jax.lax.dynamic_index_in_dim(
                a, i, keepdims=False
            )
            s_hi, s_lo, s_ts = pick(b_hi), pick(b_lo), pick(b_ts)
            s_vals, s_valid = pick(b_vals), pick(b_valid)
            s_wm = pick(wm_l)
            wm_b = st.watermark
            late0, cap0 = st.dropped_late, st.dropped_capacity
            st, a, kg = mask_update_shard(
                st, spec, kg_start, kg_end, s_hi, s_lo, s_ts, s_vals,
                s_valid, s_wm, maxp, insert=insert, kg_fill=kg_fill,
                clear_rows=pend, kg_res=kg_res,
            )
            st, pend, cf = wk.advance_and_fire_resident(
                st, spec.win, spec.red, s_wm, reduced=reduced
            )
            fires = jax.tree_util.tree_map(
                lambda buf, v: jax.lax.dynamic_update_index_in_dim(
                    buf, v, i, 0
                ),
                fires, cf,
            )
            if drain_stats:
                row = _slot_drain_stats(st, spec, s_valid, a, kg, cf,
                                        wm_b, late0, cap0)
                ds = jax.lax.dynamic_update_index_in_dim(ds, row, i, 0)
            return (i + 1, cur, st, pend, act + a,
                    kgf + kg if kg_fill else kgf, fires, ds)

        i_fin, _cur, state, pend, act, kgf, fires, ds = \
            jax.lax.while_loop(
                cond, body,
                (jnp.int32(0), cursor, state, pend0,
                 jnp.zeros((), jnp.int32), kgf0, fires0, ds0),
            )
        state = wk.apply_pending_purge(state, spec.win, spec.red, pend)
        ovf_n = state.ovf_n
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        out = (
            pack(state), ovf_n[None], act[None], kgf[None], pack(fires),
            i_fin[None],
        )
        if drain_stats:
            out += (ds[None],)
        return out

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            # per-shard cursor/base/staged vectors: each shard trips on
            # its OWN publish frontier (no collectives in the body, so
            # divergent trip counts cannot deadlock anything)
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            # [D, n_shards, cap] stacks SPLIT on the shard axis
            P(None, SHARD_AXIS), P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(SHARD_AXIS),             # wmv [n_shards, D]
        ) + ((P(),) if tiered else ()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS))
        + ((P(SHARD_AXIS),) if drain_stats else ()),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0, 5 * D + 2))
    def drain(state, *flat):
        if tiered:
            *batches, wmv, cursor, base, staged, kg_res = flat
            tail = (kg_res,)
        else:
            *batches, wmv, cursor, base, staged = flat
            tail = ()
        stacks = _fused_batch_stack(D, batches)
        res = sharded(
            state, starts, ends, jnp.asarray(cursor, jnp.int32),
            jnp.asarray(base, jnp.int32), jnp.asarray(staged, jnp.int32),
            *stacks, wmv, *tail,
        )
        st, ovf_n, act, kgf, fires, consumed = res[:6]
        if drain_stats:
            return st, (ovf_n, act, kgf), fires, consumed, res[6]
        return st, (ovf_n, act, kgf), fires, consumed

    drain.k_steps = D
    drain.ring_depth = D
    drain.max_slots = D
    drain.resident_drain = True
    drain.sharded_drain = True
    drain.while_drain = True
    drain.fused_fire = True
    drain.fused_fire_reduced = reduced
    drain.drain_stats = drain_stats
    drain.tiered = tiered
    return drain


def build_window_dcn_resident_drain(ctx: MeshContext,
                                    spec: WindowStageSpec,
                                    batch_per_device: int,
                                    depth: int,
                                    capacity_factor: float = 2.0,
                                    insert: bool = True,
                                    drain_stats: bool = False):
    """Per-host DCN-resident drain (ISSUE 20 tentpole b): the lockstep
    DCN step (runtime/dcn.py DCNWindowRunner._build_step) promoted to a
    count-gated multi-slot drain — each lockstep ROUND retires up to
    ``depth`` locally-polled batches in ONE dispatch, with the keyed
    all_to_all still running per slot and the cross-host control plane
    (global watermark / done / fire backlog pmin-pmax) evaluated at the
    DRAIN BOUNDARY.

    The trip count is agreed ON DEVICE: every host passes its own local
    fill in ``fills`` and the kernel takes ``pmax`` over the shard axis
    before the slot loop, so all hosts enter the same number of
    all_to_all rounds (a host with a shallower ring pads empty-valid
    slots) without any host-side count exchange — the collective fabric
    that moves the records also synchronizes the drain shape.

    Signature: ``drain(state, hi, lo, ts, values, valid, wm, done,
    fills)`` with [depth, B] batch stacks SPLIT over the global mesh on
    the lane axis, wm int32 [depth, n_shards] split on the shard axis,
    done/fills int32 [n_shards]. Returns ``(state', fires, stop,
    drained)``: fires stacked [n_shards, depth] for the runner's
    per-slot ``_emit_local``, stop the lockstep termination conjunction
    (gdone and no fire backlog in any live slot), drained the agreed
    slot count — the host scales the NEXT boundary's peer-exchange
    frame deadline by it. With ``drain_stats`` a fifth element rides
    along: the [n_shards, depth, N] per-slot recorder stack."""
    from flink_tpu.parallel.exchange import bucket_capacity

    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    n = ctx.n_shards
    cap = bucket_capacity(batch_per_device, n, capacity_factor)
    D = int(depth)
    F = spec.win.fires_per_step

    def shard_body(state, kg_start, kg_end, fills, done, hi, lo, ts,
                   values, valid, wm):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        # the drain shape is a GLOBAL agreement: deepest local ring
        # wins, shallower hosts run empty-valid pad slots — replicated
        # by construction, so every host's all_to_all count matches
        count = jax.lax.pmax(fills[0], SHARD_AXIS)
        gdone = jax.lax.pmin(done[0], SHARD_AXIS)
        wm_l = wm[:, 0]                    # [D] this shard's wm column

        def sub(carry, xs):
            i, s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs

            def live(st):
                # per-slot global low watermark: decisions ride the
                # same fabric as the records (lockstep invariant)
                gwm = jax.lax.pmin(s_wm, SHARD_AXIS)
                wm_b = st.watermark
                late0, cap0 = st.dropped_late, st.dropped_capacity
                st, act = exchange_update_shard(
                    st, spec, kg_start, kg_end, s_hi, s_lo, s_ts,
                    s_vals, s_valid, n, maxp, cap, insert=insert,
                )
                st, fr = wk.advance_and_fire(st, spec.win, spec.red,
                                             gwm)
                cf = wk.compact_fires(st.table, fr)
                # fire backlog: full on-time lanes mean more window
                # ends may be due — the ensemble must keep cycling
                pending = (
                    jnp.sum(fr.lane_valid[:F], dtype=jnp.int32)
                    >= jnp.int32(F)
                ).astype(jnp.int32)
                if drain_stats:
                    kgf = jnp.zeros(0, jnp.int32)
                    ds = _slot_drain_stats(st, spec, s_valid, act, kgf,
                                           cf, wm_b, late0, cap0)
                    return st, (cf, pending, ds)
                return st, (cf, pending)

            def skip(st):
                ys = (_zero_slot_fires(spec, False),
                      jnp.zeros((), jnp.int32))
                if drain_stats:
                    ys += (jnp.zeros(len(DRAIN_STAT_FIELDS), jnp.int32),)
                return st, ys

            return jax.lax.cond(i < count, live, skip, carry)

        state, ys = jax.lax.scan(
            sub, state,
            (jnp.arange(D, dtype=jnp.int32), hi, lo, ts, values, valid,
             wm_l),
        )
        cfs, pendings = ys[0], ys[1]
        # any live slot with a full fire-lane set keeps the ensemble
        # stepping (conservative: terminates once fires run dry)
        gpending = jax.lax.pmax(jnp.max(pendings), SHARD_AXIS)
        stop = gdone * (1 - gpending)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        out = (pack(state), pack(cfs), stop, count)
        if drain_stats:
            out += (ys[2][None],)
        return out

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(SHARD_AXIS),             # fills: per-host ring occupancy
            P(SHARD_AXIS),             # done flags
            # [D, B] batch stacks SPLIT over the global mesh on the
            # lane axis: each host's records sit on its local devices
            P(None, SHARD_AXIS), P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(None, SHARD_AXIS),       # wm [D, n_shards]
        ),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P())
        + ((P(SHARD_AXIS),) if drain_stats else ()),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def drain(state, hi, lo, ts, values, valid, wm, done, fills):
        res = sharded(
            state, starts, ends, jnp.asarray(fills, jnp.int32),
            jnp.asarray(done, jnp.int32), hi, lo, ts, values, valid,
            wm,
        )
        if drain_stats:
            return res[0], res[1], res[2], res[3], res[4]
        return res[:4]

    drain.k_steps = D
    drain.ring_depth = D
    drain.resident_drain = True
    drain.dcn_resident = True
    drain.recv_lanes = n * cap
    drain.bucket_cap = cap
    drain.drain_stats = drain_stats
    return drain


def _chain_fires_to_lanes(cf, n_lanes: int):
    """Re-key CompactFires into the NEXT stage's input lanes (the
    inter-stage edge of the chained drain, ISSUE 16): every fired
    (key, window) pair becomes one record keyed by the SAME key with
    event time ``window_end - 1`` — the newest instant the fired window
    covers, so a multi-level rollup lands each upstream result in
    exactly the downstream pane its window closed in (the reference's
    re-keyed DataStream between two WindowOperators).

    Accepts one slot's fires ([F, C] planes) or a whole drain's STACKED
    fires ([D, F, C]): leading axes flatten into a single plane list,
    so one pass packs an entire drain's upstream output — the shape the
    per-drain stage tail (_chained_stage_tail) feeds it.

    Compaction exploits that CompactFires lanes are already PREFIX-
    packed per plane (live lanes are the first ``counts[f]`` of plane
    ``f``), so the edge never touches the payload wholesale: a cumsum
    over the per-plane counts gives plane offsets, a searchsorted over
    those offsets maps each of the ``n_lanes`` output slots to its
    (plane, lane) source, and three O(E) gathers pull the rows — no
    sort, no scatter, nothing proportional to F*C, so the edge adds
    nothing to the op-budget ledger's scatter/sort counts and stays
    cheap at large capacities. Lanes beyond ``n_lanes`` (an over-full
    edge) are counted in ``dropped`` so the executor's strict-capacity
    accounting sees them; identity re-keying keeps every fired key in
    its owning shard's key-group range, so the packed lanes feed the
    local next-stage update with ZERO collectives."""
    C = int(cf.key_hi.shape[-1])
    Pn = 1
    for d in cf.counts.shape:
        Pn *= int(d)
    counts = cf.counts.reshape(Pn)
    lane_valid = cf.lane_valid.reshape(Pn)
    ends = cf.window_end_ticks.reshape(Pn)
    key_hi = cf.key_hi.reshape(Pn, C)
    key_lo = cf.key_lo.reshape(Pn, C)
    out_shape = tuple(cf.values.shape[cf.counts.ndim + 1:])
    values = cf.values.reshape((Pn, C) + out_shape)
    E = int(n_lanes)
    live_counts = jnp.where(
        lane_valid, jnp.minimum(counts, jnp.int32(C)), jnp.int32(0)
    )
    offs = jnp.cumsum(live_counts)
    total = offs[-1]
    starts = offs - live_counts
    ar = jnp.arange(E, dtype=jnp.int32)
    f_sel = jnp.clip(jnp.searchsorted(offs, ar + 1), 0, Pn - 1)
    idx = jnp.clip(ar - starts[f_sel], 0, C - 1)
    ok = ar < total
    hi = jnp.where(ok, key_hi[f_sel, idx], jnp.uint32(0))
    lo = jnp.where(ok, key_lo[f_sel, idx], jnp.uint32(0))
    ts = jnp.where(ok, ends[f_sel] - jnp.int32(1), jnp.int32(0))
    okv = ok.reshape((E,) + (1,) * len(out_shape))
    vals = jnp.where(okv, values[f_sel, idx], jnp.zeros((), values.dtype))
    dropped = jnp.maximum(total - jnp.int32(E), 0)
    # ``total`` is the edge DEMAND (upstream fire lanes offered,
    # pre-clamp) — the stage flight recorder reports it against the
    # exchange-lanes budget so a near-overflow edge is visible before
    # it drops (ISSUE 17)
    return hi, lo, ts, vals, ok, dropped, total


def _chain_stage_watermark(up_wm, up_state, up_spec: WindowStageSpec):
    """Downstream watermark for the stage fed by ``up_state``'s fires.

    The upstream stage has fired panes through ``fired_through``; every
    FUTURE fire comes from a pane > fired_through, whose re-keyed record
    carries ts = (pane + 1) * slide - 1 >= (fired_through + 2) * slide
    - 1. Capping the downstream watermark at that horizon minus one
    guarantees no inter-stage record is ever late at the next stage —
    the stage tail inserts the whole drain's edge records BEFORE its
    single advance, and the cap is monotone in ``fired_through``, so
    records arriving in a LATER drain also beat this drain's cap. The
    outer min keeps the job watermark contract: a downstream window
    never closes past what the source watermark allows."""
    slide = int(up_spec.win.slide_ticks)
    # fired_through jumps to the WATERMARK pane once the upstream
    # backlog clears (end-of-stream flush: ~2^31/slide), so the
    # horizon multiply must clamp first or it wraps int32 negative and
    # pins the downstream watermark below the final windows forever
    ft_cap = (2**31 - 4) // slide - 2
    ft = jnp.clip(up_state.fired_through, jnp.int32(-1), jnp.int32(ft_cap))
    horizon = (ft + 2) * jnp.int32(slide) - 2
    return jnp.minimum(up_wm, horizon)


def _chained_slot_body(stage0, spec0, kg_start, kg_end, maxp, s_hi, s_lo,
                       s_ts, s_vals, s_valid, s_wm, insert, kg_fill,
                       drain_stats=False):
    """One live slot of the chained drain's stage-0 scan: consume the
    staged batch exactly like the single-stage resident body and emit
    this slot's CompactFires for the scan to stack. Downstream stages
    deliberately do NOT run here — they run ONCE per drain over the
    stacked fires (_chained_stage_tail), which is the chained drain's
    whole cost model. With ``drain_stats`` the slot also emits its
    DRAIN_STAT_FIELDS vector — the stage-0 half of the stage-aware
    flight recorder (ISSUE 17), identical to the single-stage payload."""
    st, pend = stage0
    wm_b = st.watermark
    late0, cap0 = st.dropped_late, st.dropped_capacity
    st, act, kgf = mask_update_shard(
        st, spec0, kg_start, kg_end, s_hi, s_lo, s_ts, s_vals,
        s_valid, s_wm, maxp, insert=insert, kg_fill=kg_fill,
        clear_rows=pend,
    )
    st, pend, cf = wk.advance_and_fire_resident(
        st, spec0.win, spec0.red, s_wm
    )
    if drain_stats:
        ds = _slot_drain_stats(st, spec0, s_valid, act, kgf, cf,
                               wm_b, late0, cap0, defer_fires=True)
        return (st, pend), (act, kgf, cf, ds)
    return (st, pend), (act, kgf, cf)


def _chained_stage_tail(down_states, specs, st0, cf_stack, wm_last,
                        kg_start, kg_end, maxp, exchange_lanes,
                        drain_stats=False):
    """Downstream stages of the chained drain, ONCE per drain — not
    once per slot. The whole drain's stacked stage-0 fires pack into a
    single ``exchange_lanes``-wide edge (_chain_fires_to_lanes over the
    [D, F, C] stack), feed ONE update and ONE advance-and-fire at the
    coupled watermark, and each further stage repeats the pattern on
    its upstream's single fire set.

    Correct because every insert precedes the stage's single advance
    (no window can close before receiving all of this drain's records
    for it), and the watermark coupling (_chain_stage_watermark) still
    guarantees across drains that no future upstream fire is late
    downstream. Fires only become host-visible after the dispatch
    returns, so deferring the downstream advance to the drain boundary
    changes no observable timing — but it changes the cost model
    completely: a second stage adds one E-lane update + one advance
    per D-slot drain instead of D of each (plus D per-slot state
    copies through the fire gate). The <15%-overhead acceptance
    criterion of ISSUE 16 lives here.

    Returns ``(down_states', final_fires)`` with ``final_fires`` a
    1-slot stacked CompactFires ([1, F, C] leaves) when the chain has
    a downstream stage — the executor's consume path reads the slot
    dimension from the payload shape, so the narrower stack needs no
    host-side change. With ``drain_stats`` a third element rides
    along: a ``[n_stages-1, len(STAGE_STAT_FIELDS)]`` int32 stack, one
    per-drain record per downstream stage (the tail runs once per
    drain, so each row IS this drain's edge/watermark story) — element
    ops and tiny reductions only, same ledger discipline as the
    per-slot payload."""
    import dataclasses as _dc

    out = []
    stage_recs = []
    up_state, up_fires, wm_up = st0, cf_stack, wm_last
    for j in range(1, len(specs)):
        wm_j = _chain_stage_watermark(wm_up, up_state, specs[j - 1])
        (c_hi, c_lo, c_ts, c_vals, c_ok, c_drop,
         c_demand) = _chain_fires_to_lanes(up_fires, exchange_lanes)
        st_j = down_states[j - 1]
        wm_b_j = st_j.watermark
        # downstream stages always insert: their key population arrives
        # through the edge, never through the ingest-staged batch the
        # fast (lookup-only) tier models
        st_j, _act_j, _kgf_j = mask_update_shard(
            st_j, specs[j], kg_start, kg_end, c_hi, c_lo, c_ts,
            c_vals, c_ok, wm_j, maxp, insert=True, kg_fill=False,
        )
        # an over-full edge drops the overflow lanes; fold them into
        # the receiving stage's capacity-drop counter so the executor's
        # strict-capacity accounting (and the drop metrics) see them
        st_j = _dc.replace(
            st_j, dropped_capacity=st_j.dropped_capacity + c_drop
        )
        st_j, pend_j, cf_j = wk.advance_and_fire_resident(
            st_j, specs[j].win, specs[j].red, wm_j
        )
        # one purge sweep per drain (instead of deferring into a next
        # update's ring reset — there is no next update this dispatch)
        st_j = wk.apply_pending_purge(
            st_j, specs[j].win, specs[j].red, pend_j
        )
        if drain_stats:
            slide_j = jnp.int32(specs[j].win.slide_ticks)
            # coupled-watermark lag behind upstream, in downstream pane
            # widths; max-0 first so an end-of-stream flush (wm near
            # int32 max) wrapping the subtraction reads 0, never junk
            lag_panes = jnp.maximum(wm_up - wm_j, jnp.int32(0)) // slide_j
            # downstream panes this advance crossed, sentinel-clamped
            # exactly like the per-slot payload (_slot_drain_stats)
            wb_j = jnp.maximum(
                wm_b_j, st_j.watermark - jnp.int32(1 << 20)
            )
            panes_j = jnp.maximum(
                jnp.int32(0),
                st_j.watermark // slide_j - wb_j // slide_j,
            )
            panes_j = jnp.where(
                wm_b_j < jnp.int32(-(2 ** 30)), jnp.int32(0), panes_j
            )
            stage_recs.append(jnp.stack([       # STAGE_STAT_FIELDS order
                c_demand,
                jnp.minimum(c_demand, jnp.int32(exchange_lanes)),
                jnp.sum(cf_j.lane_valid, dtype=jnp.int32),
                c_drop,
                lag_panes,
                panes_j,
            ]))
        out.append(st_j)
        up_state, wm_up = st_j, wm_j
        up_fires = jax.tree_util.tree_map(lambda x: x[None], cf_j)
    if drain_stats:
        ss = jnp.stack(stage_recs)      # [n_stages-1, N_STAGE_FIELDS]
        return tuple(out), up_fires, ss
    return tuple(out), up_fires


def build_window_chained_drain(ctx: MeshContext,
                               specs: Sequence[WindowStageSpec],
                               depth: int, insert: bool = True,
                               kg_fill: bool = False,
                               exchange_lanes: int = 1024,
                               drain_stats: bool = False):
    """Multi-stage resident ring drain (stage-graph subsystem, ISSUE
    16): ONE jitted dispatch consumes up to ``depth`` staged ring slots
    through a CHAIN of keyed window stages — stage 0 applies the staged
    batch exactly like build_window_resident_drain's body (the same
    count-gated slot scan), stacking each slot's CompactFires; then
    each downstream stage runs ONCE per drain (_chained_stage_tail):
    the whole stack of upstream fires is re-keyed on device
    (_chain_fires_to_lanes: a cumsum+searchsorted+gather pack over the
    stacked fire planes) and applied in one update + one
    advance-and-fire at the coupled watermark. A keyBy→window→keyBy→
    window pipeline (sessionize→aggregate, multi-level rollup)
    therefore still costs one host dispatch per ring drain — the
    Hazelcast-Jet saturation criterion the ISSUE names: chaining must
    not reintroduce per-stage host round trips — and the second stage
    adds one edge pack + E-lane update + advance per DRAIN, not per
    slot (fires only become host-visible when the dispatch returns, so
    the deferral changes no observable timing).

    Inter-stage edge: identity re-key. A fired key keeps its key bits,
    so it hashes to the same key group and stays on its owning shard —
    the per-shard exchange is a local pack, no all_to_all, and the
    sharded variant keeps its zero-collective body. ``exchange_lanes``
    bounds the PER-DRAIN edge width (pipeline.stages.exchange-lanes —
    size it at distinct keys x panes closing per drain); overflow
    lanes count into the downstream stage's dropped_capacity so a
    too-narrow edge is loudly visible, never silent.

    Watermark coupling: stage j+1 advances to ``min(upstream wm,
    (fired_through_j + 2) * slide_j - 2)`` (_chain_stage_watermark) so
    no future upstream fire can be late downstream — the exactly-once
    cut at a drain boundary then needs no in-flight edge payload: every
    fire the upstream state counts as fired has been folded into the
    downstream state within the same dispatch.

    Signature: ``drain(states, hi_0, lo_0, ticks_0, values_0, valid_0,
    ..., wmv, count)`` — ``states`` a TUPLE of per-stage stacked window
    states (donated as one buffer set), batch operands exactly as
    build_window_resident_drain. Returns ``(states', (ovf_n, activity,
    kg_fill), fires)`` with ``fires`` the FINAL stage's CompactFires
    stacked [n_shards, 1] (one tail advance per drain) — the
    executor's lagged consume_fires path reads the slot dimension from
    the payload shape, so the chain's output needs no host change.
    With ``drain_stats`` (observability.drain-stats, ISSUE 17) a
    fourth return element rides along: the PAIR ``(ds0, ss)`` — the
    stage-0 per-slot [n_shards, depth, len(DRAIN_STAT_FIELDS)] flight-
    recorder stack exactly as the single-stage drain emits it, plus a
    per-downstream-stage [n_stages-1, n_shards,
    len(STAGE_STAT_FIELDS)] record of this drain's edge/watermark
    story; off, arity and op budgets are byte-identical to pre-
    telemetry (op_budget_pre_stage_stats.json pins it)."""
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    D = int(depth)
    specs = tuple(specs)

    def shard_body(states, kg_start, kg_end, count, hi, lo, ts, values,
                   valid, wm):
        states = jax.tree_util.tree_map(lambda x: x[0], states)
        kg_start, kg_end = kg_start[0], kg_end[0]
        carry0 = (states[0], jnp.zeros(specs[0].win.ring, bool))

        def sub(carry, xs):
            i, s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs

            def live(op):
                return _chained_slot_body(
                    op, specs[0], kg_start, kg_end, maxp, s_hi, s_lo,
                    s_ts, s_vals, s_valid, s_wm, insert, kg_fill,
                    drain_stats=drain_stats,
                )

            def skip(op):
                kgf = jnp.zeros(maxp if kg_fill else 0, jnp.int32)
                ys = (jnp.zeros((), jnp.int32), kgf,
                      _zero_slot_fires(specs[0], False))
                if drain_stats:
                    ys += (jnp.zeros(len(DRAIN_STAT_FIELDS), jnp.int32),)
                return op, ys

            return jax.lax.cond(i < count, live, skip, carry)

        wm_vec = wm[0]
        carry, ys = jax.lax.scan(
            sub, carry0,
            (jnp.arange(D, dtype=jnp.int32), hi, lo, ts, values, valid,
             wm_vec),
        )
        acts, kgfs, cf_stack = ys[:3]
        st0 = wk.apply_pending_purge(
            carry[0], specs[0].win, specs[0].red, carry[1]
        )
        # effective drain watermark: MAX over LIVE slots — update-only
        # slots (and the dispatch pad) carry the MIN-int "no watermark"
        # sentinel, so the last slot is not necessarily the target
        live_mask = jnp.arange(D, dtype=jnp.int32) < count
        wm_last = jnp.max(jnp.where(
            live_mask, wm_vec, jnp.int32(-(2**31) + 1)
        ))
        tail = _chained_stage_tail(
            states[1:], specs, st0, cf_stack, wm_last, kg_start,
            kg_end, maxp, exchange_lanes, drain_stats=drain_stats,
        )
        down, fires = tail[0], tail[1]
        states = (st0,) + down
        ovf_n = states[0].ovf_n
        act = jnp.sum(acts)
        kgf = kgfs.sum(axis=0) if kg_fill else jnp.zeros(0, jnp.int32)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        out = (
            pack(states), ovf_n[None], act[None], kgf[None], pack(fires),
        )
        if drain_stats:
            # [1, D, N] per-slot stack (deferred fire columns filled
            # from the stacked fires) + [1, S-1, K] per-stage records
            ds0 = _deferred_fire_columns(ys[3], cf_stack)
            out += (ds0[None], tail[2][None])
        return out

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(),                       # count: replicated scalar cursor
            P(), P(), P(), P(), P(),   # [D, B] batch stacks, replicated
            P(SHARD_AXIS),             # wmv [n_shards, D]
        ),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS))
        + ((P(SHARD_AXIS), P(SHARD_AXIS)) if drain_stats else ()),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def drain(states, *flat):
        *batches, wmv, count = flat
        stacks = _fused_batch_stack(D, batches)
        res = sharded(
            states, starts, ends, jnp.asarray(count, jnp.int32),
            *stacks, wmv,
        )
        st, ovf_n, act, kgf, fires = res[:5]
        if drain_stats:
            # stage records transpose to the documented
            # [n_stages-1, n_shards, K] block (element op only)
            return st, (ovf_n, act, kgf), fires, (
                res[5], jnp.swapaxes(res[6], 0, 1)
            )
        return st, (ovf_n, act, kgf), fires

    drain.k_steps = D
    drain.ring_depth = D
    drain.resident_drain = True
    drain.chained_drain = True
    drain.n_stages = len(specs)
    drain.exchange_lanes = int(exchange_lanes)
    drain.fused_fire = True
    drain.fused_fire_reduced = False
    drain.drain_stats = drain_stats
    return drain


def build_window_chained_drain_sharded(ctx: MeshContext,
                                       specs: Sequence[WindowStageSpec],
                                       depth: int, insert: bool = True,
                                       kg_fill: bool = False,
                                       exchange_lanes: int = 1024,
                                       drain_stats: bool = False):
    """Data-parallel chained drain: the multi-stage chain of
    build_window_chained_drain lowered over build_window_sharded_drain's
    shard-local geometry — per-shard pre-routed lane slices, per-shard
    count VECTOR, and still ZERO cross-chip collectives in the body:
    the identity re-key keeps every inter-stage record on the shard
    that fired it (same key → same key group → same owner), so the
    chained edge is a local pack and divergent per-shard counts stay
    safe exactly as in the single-stage sharded drain."""
    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh
    D = int(depth)
    specs = tuple(specs)

    def shard_body(states, kg_start, kg_end, counts, hi, lo, ts, values,
                   valid, wm):
        states = jax.tree_util.tree_map(lambda x: x[0], states)
        kg_start, kg_end = kg_start[0], kg_end[0]
        count = counts[0]          # this shard's OWN fill level
        carry0 = (states[0], jnp.zeros(specs[0].win.ring, bool))

        def sub(carry, xs):
            i, s_hi, s_lo, s_ts, s_vals, s_valid, s_wm = xs

            def live(op):
                return _chained_slot_body(
                    op, specs[0], kg_start, kg_end, maxp, s_hi, s_lo,
                    s_ts, s_vals, s_valid, s_wm, insert, kg_fill,
                    drain_stats=drain_stats,
                )

            def skip(op):
                kgf = jnp.zeros(maxp if kg_fill else 0, jnp.int32)
                ys = (jnp.zeros((), jnp.int32), kgf,
                      _zero_slot_fires(specs[0], False))
                if drain_stats:
                    ys += (jnp.zeros(len(DRAIN_STAT_FIELDS), jnp.int32),)
                return op, ys

            return jax.lax.cond(i < count, live, skip, carry)

        wm_vec = wm[0]
        carry, ys = jax.lax.scan(
            sub, carry0,
            # [D, 1, cap] per-shard batch stacks squeeze the split axis
            (jnp.arange(D, dtype=jnp.int32), hi[:, 0], lo[:, 0],
             ts[:, 0], values[:, 0], valid[:, 0], wm_vec),
        )
        acts, kgfs, cf_stack = ys[:3]
        st0 = wk.apply_pending_purge(
            carry[0], specs[0].win, specs[0].red, carry[1]
        )
        # per-shard effective drain watermark: MAX over this shard's
        # LIVE slots (divergent counts are safe — each shard's tail
        # advances under its own target, same as the per-slot scan)
        live_mask = jnp.arange(D, dtype=jnp.int32) < count
        wm_last = jnp.max(jnp.where(
            live_mask, wm_vec, jnp.int32(-(2**31) + 1)
        ))
        tail = _chained_stage_tail(
            states[1:], specs, st0, cf_stack, wm_last, kg_start,
            kg_end, maxp, exchange_lanes, drain_stats=drain_stats,
        )
        down, fires = tail[0], tail[1]
        states = (st0,) + down
        ovf_n = states[0].ovf_n
        act = jnp.sum(acts)
        kgf = kgfs.sum(axis=0) if kg_fill else jnp.zeros(0, jnp.int32)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        out = (
            pack(states), ovf_n[None], act[None], kgf[None], pack(fires),
        )
        if drain_stats:
            ds0 = _deferred_fire_columns(ys[3], cf_stack)
            out += (ds0[None], tail[2][None])
        return out

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(SHARD_AXIS),             # counts: per-shard fill levels
            P(None, SHARD_AXIS), P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(None, SHARD_AXIS), P(None, SHARD_AXIS),
            P(SHARD_AXIS),             # wmv [n_shards, D]
        ),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                   P(SHARD_AXIS), P(SHARD_AXIS))
        + ((P(SHARD_AXIS), P(SHARD_AXIS)) if drain_stats else ()),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def drain(states, *flat):
        *batches, wmv, counts = flat
        stacks = _fused_batch_stack(D, batches)
        res = sharded(
            states, starts, ends, jnp.asarray(counts, jnp.int32),
            *stacks, wmv,
        )
        st, ovf_n, act, kgf, fires = res[:5]
        if drain_stats:
            return st, (ovf_n, act, kgf), fires, (
                res[5], jnp.swapaxes(res[6], 0, 1)
            )
        return st, (ovf_n, act, kgf), fires

    drain.k_steps = D
    drain.ring_depth = D
    drain.resident_drain = True
    drain.sharded_drain = True
    drain.chained_drain = True
    drain.n_stages = len(specs)
    drain.exchange_lanes = int(exchange_lanes)
    drain.fused_fire = True
    drain.fused_fire_reduced = False
    drain.drain_stats = drain_stats
    return drain


def build_window_fire_step(ctx: MeshContext, spec: WindowStageSpec):
    """Fire-only half: advance the watermark, evaluate due window ends for
    the whole key population, and return device-compacted fires
    (wk.CompactFires). Called by the host only at pane-boundary crossings
    (or to drain at checkpoints / end of stream)."""
    mesh = ctx.mesh

    def shard_body(state, wm):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        state, fr = wk.advance_and_fire(state, spec.win, spec.red, wm[0])
        cf = wk.compact_fires(state.table, fr)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        return pack(state), pack(cf)

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def fire_step(state, wm):
        return sharded(state, wm)

    return fire_step


def build_window_fire_reduced_step(ctx: MeshContext, spec: WindowStageSpec):
    """Fire step whose output is reduced on device to per-lane scalars
    (wk.ReducedFires): no key/value packing at all. Used by the executor
    when every sink is device_reduce-capable and the spill tier is empty —
    the common high-throughput analytics topology. The pack scatters this
    avoids are ~4x the cost of the whole watermark advance on a 1M-slot
    shard, and the drain's device->host traffic drops to five [Ft] fields."""
    mesh = ctx.mesh

    def shard_body(state, wm):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        state, fr = wk.advance_and_fire(state, spec.win, spec.red, wm[0])
        rf = wk.reduce_fires(fr)
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        return pack(state), pack(rf)

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def fire_step(state, wm):
        return sharded(state, wm)

    return fire_step


def build_kg_occupancy_step(ctx: MeshContext, spec: WindowStageSpec):
    """Per-key-group live-key occupancy over the mesh (wk.kg_occupancy):
    int32 [n_shards, max_parallelism], shards own disjoint groups so the
    host's per-group view is the sum over the shard axis. State is NOT
    donated — the telemetry read must never invalidate the live buffers.
    Compiled lazily by the executor and run at fire boundaries on a wall-
    clock budget (observability.kg-stats-interval-ms), where the barrier
    fetch already syncs the loop."""
    mesh = ctx.mesh
    maxp = ctx.max_parallelism

    def shard_body(state):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        return wk.kg_occupancy(state, maxp, red=spec.red,
                               win=spec.win)[None]

    sharded = shard_map(
        shard_body, mesh=mesh, in_specs=(P(SHARD_AXIS),),
        out_specs=P(SHARD_AXIS), check_vma=False,
    )

    @jax.jit
    def occupancy_step(state):
        return sharded(state)

    return occupancy_step


def build_compact_step(ctx: MeshContext, spec: WindowStageSpec):
    """Whole-shard table compaction (wk.compact_table) over the mesh; run
    by the host at fire boundaries when the overflow ring reported
    pressure (the RocksDB-compaction analog)."""
    mesh = ctx.mesh

    def shard_body(state):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        state = wk.compact_table(state, spec.win, spec.red)
        return jax.tree_util.tree_map(lambda x: x[None], state)

    sharded = shard_map(
        shard_body, mesh=mesh, in_specs=(P(SHARD_AXIS),),
        out_specs=P(SHARD_AXIS), check_vma=False,
    )

    @partial(jax.jit, donate_argnums=(0,))
    def compact_step(state):
        return sharded(state)

    return compact_step


def clear_overflow(state):
    """Host-side: zero the overflow counter after draining the ring (the
    entry arrays may keep stale rows — only [:ovf_n] is ever read)."""
    import dataclasses as _dc

    return _dc.replace(
        state,
        ovf_n=jax.device_put(
            np.zeros(state.ovf_n.shape, np.int32), state.ovf_n.sharding
        ),
    )


def clear_dirty(state):
    """Host-side: reset the changelog dirty bits after a checkpoint staged
    its device fetch — everything mutated from here on belongs to the NEXT
    delta. Cheap device_put of a tiny bool plane (cf. clear_overflow)."""
    import dataclasses as _dc

    if state.kg_dirty.shape[-1] == 0:
        return state
    return _dc.replace(
        state,
        kg_dirty=jax.device_put(
            np.zeros(state.kg_dirty.shape, bool), state.kg_dirty.sharding
        ),
    )


def watermark_vector(ctx: MeshContext, wm: int):
    return jnp.full((ctx.n_shards,), np.int32(wm))


# -------------------------------------------------------- session windows

@dataclass
class SessionStageSpec:
    red: "object"
    gap_ticks: int = 1000
    capacity_per_shard: int = 1 << 16
    probe_len: int = 16


def init_session_state(ctx: MeshContext, spec: SessionStageSpec):
    from flink_tpu.ops import session_windows as sw

    states = [
        sw.init_state(spec.capacity_per_shard, spec.probe_len, spec.red)
        for _ in range(ctx.n_shards)
    ]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)
    return jax.device_put(stacked, ctx.state_sharding)


def build_session_step(ctx: MeshContext, spec: SessionStageSpec):
    from flink_tpu.ops import session_windows as sw

    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh

    def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid, wm):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg = assign_to_key_group(route_hash(hi, lo, jnp), maxp, jnp)
        mine = valid & (kg >= kg_start.astype(jnp.uint32)) & (
            kg <= kg_end.astype(jnp.uint32)
        )
        state, old_f, mid_f, wm_f = sw.update_and_fire(
            state, spec.red, spec.gap_ticks, hi, lo, ts, values, mine, wm[0]
        )
        pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        return pack(state), pack(old_f), pack(mid_f), pack(wm_f)

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(), P(), P(), P(SHARD_AXIS),
        ),
        out_specs=(P(SHARD_AXIS),) * 4,
        check_vma=False,
    )

    @jax.jit
    def step(state, hi, lo, ts, values, valid, wm):
        return sharded(state, starts, ends, hi, lo, ts, values, valid, wm)

    return step


# ---------------------------------------------------------- count windows

@dataclass
class CountStageSpec:
    red: "object"
    n_per_window: int = 100
    capacity_per_shard: int = 1 << 16
    probe_len: int = 16


def init_count_state(ctx: MeshContext, spec: CountStageSpec):
    from flink_tpu.ops import count_windows as cw

    states = [
        cw.init_state(spec.capacity_per_shard, spec.probe_len, spec.red)
        for _ in range(ctx.n_shards)
    ]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)
    return jax.device_put(stacked, ctx.state_sharding)


def build_count_step(ctx: MeshContext, spec: CountStageSpec):
    from flink_tpu.ops import count_windows as cw

    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh

    def shard_body(state, kg_start, kg_end, hi, lo, values, valid):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg = assign_to_key_group(route_hash(hi, lo, jnp), maxp, jnp)
        mine = valid & (kg >= kg_start.astype(jnp.uint32)) & (
            kg <= kg_end.astype(jnp.uint32)
        )
        state, khi, klo, w, vals, mask = cw.update(
            state, spec.red, spec.n_per_window, hi, lo, values, mine
        )
        pack = lambda x: x[None]
        state = jax.tree_util.tree_map(pack, state)
        return state, pack(khi), pack(klo), pack(w), pack(vals), pack(mask)

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(), P(),
        ),
        out_specs=tuple([P(SHARD_AXIS)] * 6),
        check_vma=False,
    )

    @jax.jit
    def step(state, hi, lo, values, valid):
        return sharded(state, starts, ends, hi, lo, values, valid)

    return step


# --------------------------------------------------------------- rolling

@dataclass
class RollingStageSpec:
    red: "object"  # wk.ReduceSpec
    capacity_per_shard: int = 1 << 16
    probe_len: int = 16


def init_rolling_state(ctx: MeshContext, spec: RollingStageSpec):
    from flink_tpu.ops import rolling

    states = [
        rolling.init_state(spec.capacity_per_shard, spec.probe_len, spec.red)
        for _ in range(ctx.n_shards)
    ]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *states)
    return jax.device_put(stacked, ctx.state_sharding)


def build_rolling_step(ctx: MeshContext, spec: RollingStageSpec):
    """Rolling keyed reduce over the mesh: per-record outputs are psum-merged
    across shards (each lane is owned by exactly one shard)."""
    from flink_tpu.ops import rolling
    from flink_tpu.ops.segment import _bshape

    starts, ends = ctx.kg_bounds()
    starts = jnp.asarray(starts)
    ends = jnp.asarray(ends)
    maxp = ctx.max_parallelism
    mesh = ctx.mesh

    def shard_body(state, kg_start, kg_end, hi, lo, values, valid):
        state = jax.tree_util.tree_map(lambda x: x[0], state)
        kg_start, kg_end = kg_start[0], kg_end[0]
        kg = assign_to_key_group(route_hash(hi, lo, jnp), maxp, jnp)
        mine = valid & (kg >= kg_start.astype(jnp.uint32)) & (
            kg <= kg_end.astype(jnp.uint32)
        )
        state, outputs, out_valid = rolling.update(
            state, spec.red, hi, lo, values, mine
        )
        outputs = jax.lax.psum(
            jnp.where(_bshape(out_valid, outputs), outputs,
                      jnp.zeros((), outputs.dtype)),
            SHARD_AXIS,
        )
        out_valid = jax.lax.psum(out_valid.astype(jnp.int32), SHARD_AXIS) > 0
        state = jax.tree_util.tree_map(lambda x: x[None], state)
        return state, outputs, out_valid

    sharded = shard_map(
        shard_body,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(), P(),
        ),
        out_specs=(P(SHARD_AXIS), P(), P()),
        check_vma=False,
    )

    @jax.jit
    def step(state, hi, lo, values, valid):
        return sharded(state, starts, ends, hi, lo, values, valid)

    return step


# --------------------------------------------- canonical kernel families

# Canonical "tiny but structurally real" dims for auditing: big enough
# that every code path (probe rounds, ring panes, overflow lanes, kg
# telemetry) is live in the traced program, small enough that tracing
# the whole grid stays inside the lint tier's wall-time budget.
AUDIT_CAPACITY = 64
AUDIT_PROBE_LEN = 4
AUDIT_BATCH = 8
AUDIT_K_STEPS = 2
# resident-drain ring depth for the audit grid: deep enough that the
# cond gate is structurally live (the canonical count operand is
# depth - 1, so BOTH branches appear in the traced program), small
# enough to stay inside the lint tier's wall-time budget
AUDIT_RING_DEPTH = 4
# per-slot inter-stage edge width for the audited chained-drain chain
AUDIT_EXCHANGE_LANES = 16


@dataclass(frozen=True)
class KernelFamily:
    """One canonical hot-path kernel family.

    The compiled-graph auditor (tools/lint trace tier, ISSUE 11) and the
    bench harness both need the same enumeration of "which step builders
    exist, along which spec axes" — this descriptor and
    :func:`kernel_family_grid` ARE that enumeration, kept next to the
    builders so the audited grid and the executor's dispatch surface
    cannot drift. ``donated`` mirrors the builder's donate_argnums
    contract (argnum 0 = state); the donation-effective rule verifies it
    against the lowered/compiled alias tables. ``deep`` marks the
    families the auditor fully compiles (executable alias table + memory
    stats) rather than just lowers — one representative per kind keeps
    the audit under its wall-time budget.
    """

    name: str
    builder: Callable
    kind: str            # update | megastep | megastep_fired |
    #                      resident_drain | fire | fire_reduced |
    #                      compact | occupancy | session | count |
    #                      rolling
    #                      (resident_drain reuses ``k_steps`` for its
    #                      ring depth — the scan length axis is the same
    #                      ledger currency either way)
    route: str = "mask"      # mask | exchange | sharded
    layout: str = "hash"     # hash | direct
    donated: bool = True
    insert: bool = True
    precombine: bool = False
    packed: bool = False
    reduced: bool = False
    k_steps: int = 0
    deep: bool = False
    # observability.drain-stats telemetry-ON variant (ISSUE 14): the
    # drain emits the per-slot DRAIN_STAT_FIELDS stack. OFF families
    # keep their pre-telemetry names AND ledger entries — the byte-
    # identity test proves the payload compiles out.
    drain_stats: bool = False
    # tiered-residency variant (ISSUE 18): the kernel takes a trailing
    # replicated kg_res bool[max_parallelism] mask and diverts lanes of
    # non-resident key-groups down the overflow ring. OFF families keep
    # their pre-tier ledger entries byte-identical — residency is data,
    # not structure.
    tiered: bool = False


def kernel_family_grid():
    """THE canonical kernel-family grid: every window step builder, along
    the spec axes the executor actually dispatches (routes x layouts x
    packed/precombine planes x fused depths), plus the auxiliary
    session/count/rolling window steps. tests/test_lint_trace.py asserts
    every ``build_*`` step factory in this module is represented, so
    adding a builder without extending the grid fails tier-1."""
    F = KernelFamily
    K = AUDIT_K_STEPS
    return [
        F("step.combined.mask.hash", build_window_step, "combined"),
        F("step.update.mask.hash", build_window_update_step,
          "update", deep=True),
        F("step.update.mask.direct", build_window_update_step,
          "update", layout="direct"),
        F("step.update.mask.hash.precombine", build_window_update_step,
          "update", precombine=True),
        F("step.update.mask.hash.packed", build_window_update_step,
          "update", packed=True),
        F("step.update_fast.mask.hash", build_window_update_step,
          "update", insert=False),
        F("step.update.exchange.hash", build_window_update_step_exchange,
          "update", route="exchange"),
        F("step.megastep.mask.hash.k2", build_window_megastep,
          "megastep", k_steps=K),
        F("step.megastep.exchange.hash.k2", build_window_megastep_exchange,
          "megastep", route="exchange", k_steps=K),
        F("step.megastep_fired.mask.hash.k2", build_window_megastep_fired,
          "megastep_fired", k_steps=K, deep=True),
        F("step.megastep_fired.mask.direct.k2", build_window_megastep_fired,
          "megastep_fired", layout="direct", k_steps=K),
        F("step.megastep_fired.mask.hash.k2.packed",
          build_window_megastep_fired,
          "megastep_fired", packed=True, k_steps=K),
        F("step.megastep_fired.mask.hash.k2.reduced",
          build_window_megastep_fired,
          "megastep_fired", reduced=True, k_steps=K),
        F("step.megastep_fired.exchange.hash.k2",
          build_window_megastep_fired_exchange,
          "megastep_fired", route="exchange", k_steps=K),
        # the device-resident ring drain (ISSUE 12): the executor
        # dispatches it along the same layout/plane/route axes as the
        # fired megastep it supersedes in steady state
        F("step.resident_drain.mask.hash.d4", build_window_resident_drain,
          "resident_drain", k_steps=AUDIT_RING_DEPTH, deep=True),
        F("step.resident_drain.mask.direct.d4",
          build_window_resident_drain,
          "resident_drain", layout="direct", k_steps=AUDIT_RING_DEPTH),
        F("step.resident_drain.mask.hash.d4.packed",
          build_window_resident_drain,
          "resident_drain", packed=True, k_steps=AUDIT_RING_DEPTH),
        F("step.resident_drain.mask.hash.d4.reduced",
          build_window_resident_drain,
          "resident_drain", reduced=True, k_steps=AUDIT_RING_DEPTH),
        F("step.resident_drain.exchange.hash.d4",
          build_window_resident_drain_exchange,
          "resident_drain", route="exchange", k_steps=AUDIT_RING_DEPTH),
        # the data-parallel shard-local drain (ISSUE 13): per-shard
        # pre-routed lane slices, per-shard count gating, ZERO
        # collectives in the keyed body (the no-host-crossing rule and
        # the op-budget ledger pin that — an all_to_all sneaking in
        # here would break divergent-count safety)
        F("step.sharded_drain.hash.d4", build_window_sharded_drain,
          "sharded_drain", route="sharded", k_steps=AUDIT_RING_DEPTH,
          deep=True),
        F("step.sharded_drain.direct.d4", build_window_sharded_drain,
          "sharded_drain", route="sharded", layout="direct",
          k_steps=AUDIT_RING_DEPTH),
        F("step.sharded_drain.hash.d4.packed", build_window_sharded_drain,
          "sharded_drain", route="sharded", packed=True,
          k_steps=AUDIT_RING_DEPTH),
        # telemetry-ON drain variants (observability.drain-stats, ISSUE
        # 14): one per drain builder. Ledgered like any family — the
        # flight recorder must stay element-ops-only, so an ON variant
        # whose sort/scatter/gather counts drift from its OFF twin is a
        # telemetry regression the op-budget rule catches
        F("step.resident_drain.mask.hash.d4.dstats",
          build_window_resident_drain,
          "resident_drain", k_steps=AUDIT_RING_DEPTH, drain_stats=True),
        F("step.resident_drain.exchange.hash.d4.dstats",
          build_window_resident_drain_exchange,
          "resident_drain", route="exchange", k_steps=AUDIT_RING_DEPTH,
          drain_stats=True),
        F("step.sharded_drain.hash.d4.dstats", build_window_sharded_drain,
          "sharded_drain", route="sharded", k_steps=AUDIT_RING_DEPTH,
          drain_stats=True),
        # tiered-residency variants (ISSUE 18): one per dispatchable
        # route through the tiered executor path. Ledgered like any
        # family — the residency mask must stay a pure element-wise
        # divert (gather + and/or), so a sort/scatter creeping into the
        # tier gate is structural drift the op-budget rule catches; OFF
        # twins stay byte-identical to the frozen ledger
        F("step.update.mask.hash.tiered", build_window_update_step,
          "update", tiered=True),
        F("step.update.exchange.hash.tiered",
          build_window_update_step_exchange,
          "update", route="exchange", tiered=True),
        F("step.megastep_fired.mask.hash.k2.tiered",
          build_window_megastep_fired,
          "megastep_fired", k_steps=K, tiered=True),
        F("step.resident_drain.mask.hash.d4.tiered",
          build_window_resident_drain,
          "resident_drain", k_steps=AUDIT_RING_DEPTH, tiered=True),
        F("step.resident_drain.exchange.hash.d4.tiered",
          build_window_resident_drain_exchange,
          "resident_drain", route="exchange", k_steps=AUDIT_RING_DEPTH,
          tiered=True),
        F("step.sharded_drain.hash.d4.tiered", build_window_sharded_drain,
          "sharded_drain", route="sharded", k_steps=AUDIT_RING_DEPTH,
          tiered=True),
        # the multi-stage chained drain (ISSUE 16): stage-N fires
        # re-keyed on device into stage-N+1's update inside the same
        # count-gated scan. The edge is gather-only (_chain_fires_to
        # _lanes) — a sort or scatter creeping into it is exactly the
        # structural drift the op-budget ledger exists to catch, and
        # the sharded variant stays collective-free (no-host-crossing)
        F("step.chained_drain.mask.hash.d4.s2",
          build_window_chained_drain,
          "chained_drain", k_steps=AUDIT_RING_DEPTH, deep=True),
        F("step.chained_drain.mask.direct.d4.s2",
          build_window_chained_drain,
          "chained_drain", layout="direct", k_steps=AUDIT_RING_DEPTH),
        F("step.chained_drain.sharded.hash.d4.s2",
          build_window_chained_drain_sharded,
          "chained_drain_sharded", route="sharded",
          k_steps=AUDIT_RING_DEPTH),
        # stage-aware flight recorder (ISSUE 17): the chained drains'
        # telemetry-ON twins — stage-0 per-slot payload + per-stage
        # edge/watermark records, all element ops, so the OFF twins
        # stay byte-identical (op_budget_pre_stage_stats.json) and the
        # ON twins match their OFF twin per op group
        F("step.chained_drain.mask.hash.d4.s2.dstats",
          build_window_chained_drain,
          "chained_drain", k_steps=AUDIT_RING_DEPTH, drain_stats=True),
        F("step.chained_drain.sharded.hash.d4.s2.dstats",
          build_window_chained_drain_sharded,
          "chained_drain_sharded", route="sharded",
          k_steps=AUDIT_RING_DEPTH, drain_stats=True),
        # the early-exit live drains (ISSUE 20a): the count-gated scan
        # lowered as a while_loop tripping on the device-visible publish
        # cursor. The body is the SAME exchange/advance/fire sequence —
        # the op-budget ledger pins that the lowering change costs no
        # sorts/scatters — and the sharded variant keeps the keyed body
        # collective-free (divergent per-shard trip counts stay safe)
        F("step.while_drain.mask.hash.d4", build_window_while_drain,
          "while_drain", k_steps=AUDIT_RING_DEPTH, deep=True),
        F("step.while_drain.sharded.hash.d4",
          build_window_while_drain_sharded,
          "while_drain_sharded", route="sharded",
          k_steps=AUDIT_RING_DEPTH),
        F("step.while_drain.mask.hash.d4.dstats", build_window_while_drain,
          "while_drain", k_steps=AUDIT_RING_DEPTH, drain_stats=True),
        # the per-host DCN-resident drain (ISSUE 20b): the lockstep DCN
        # body run depth times per dispatch with the trip count
        # pmax-agreed on device — the all_to_all count per slot is the
        # structural invariant the signature ledger pins
        F("step.dcn_resident.hash.d4", build_window_dcn_resident_drain,
          "dcn_resident", route="exchange", k_steps=AUDIT_RING_DEPTH),
        F("step.dcn_resident.hash.d4.dstats",
          build_window_dcn_resident_drain,
          "dcn_resident", route="exchange", k_steps=AUDIT_RING_DEPTH,
          drain_stats=True),
        F("step.fire.hash", build_window_fire_step, "fire", deep=True),
        F("step.fire_reduced.hash", build_window_fire_reduced_step,
          "fire_reduced"),
        F("step.compact.hash", build_compact_step, "compact", deep=True),
        F("step.occupancy.hash", build_kg_occupancy_step,
          "occupancy", donated=False),
        # auxiliary window kinds: their steps do not donate today (the
        # audit mirrors the builders' real contracts, it does not wish)
        F("step.session.mask.hash", build_session_step,
          "session", donated=False),
        F("step.count.mask.hash", build_count_step,
          "count", donated=False),
        F("step.rolling.mask.hash", build_rolling_step,
          "rolling", donated=False),
    ]


def audit_stage_spec(fam: KernelFamily):
    """The canonical stage spec for one family: fixed tiny dims,
    family-specific layout/precombine/packed axes (spec class chosen by
    the family's window kind)."""
    red = wk.ReduceSpec("sum", jnp.float32)
    if fam.kind == "session":
        return SessionStageSpec(
            red=red, gap_ticks=16,
            capacity_per_shard=AUDIT_CAPACITY, probe_len=AUDIT_PROBE_LEN,
        )
    if fam.kind == "count":
        return CountStageSpec(
            red=red, n_per_window=4,
            capacity_per_shard=AUDIT_CAPACITY, probe_len=AUDIT_PROBE_LEN,
        )
    if fam.kind == "rolling":
        return RollingStageSpec(
            red=red,
            capacity_per_shard=AUDIT_CAPACITY, probe_len=AUDIT_PROBE_LEN,
        )
    if fam.kind in ("chained_drain", "chained_drain_sharded"):
        # a 2-stage rollup chain: stage 0 at the canonical tiny dims,
        # stage 1 a coarser tumbling window over the re-keyed fires.
        # The identity re-key preserves the key bits, so the direct-
        # index contract (hi == 0, lo < capacity) holds downstream
        # whenever it holds at ingest — both stages share the layout
        s0 = WindowStageSpec(
            win=wk.WindowSpec(4, 2, ring=4, fires_per_step=2),
            red=red,
            capacity_per_shard=AUDIT_CAPACITY, probe_len=AUDIT_PROBE_LEN,
            layout=fam.layout, precombine=fam.precombine,
            packed=fam.packed,
        )
        s1 = WindowStageSpec(
            win=wk.WindowSpec(8, 4, ring=4, fires_per_step=2),
            red=wk.ReduceSpec("sum", jnp.float32),
            capacity_per_shard=AUDIT_CAPACITY, probe_len=AUDIT_PROBE_LEN,
            layout=fam.layout,
        )
        return (s0, s1)
    win = wk.WindowSpec(4, 2, ring=4, fires_per_step=2, overflow=4)
    return WindowStageSpec(
        win=win, red=red,
        capacity_per_shard=AUDIT_CAPACITY, probe_len=AUDIT_PROBE_LEN,
        layout=fam.layout, precombine=fam.precombine, packed=fam.packed,
    )


def _family_example_args(fam: KernelFamily, ctx: MeshContext, state,
                         batch: int):
    """A canonical concrete call for ``fam``: batch operands with the
    exact dtypes the executor stages (uint32 keys, int32 ticks, f32
    values, bool valid, int32 watermark vectors). Direct layout keeps
    the identity-key contract (hi == 0, lo < capacity)."""
    B = batch
    if fam.layout == "direct":
        hi = jnp.zeros(B, jnp.uint32)
        lo = jnp.arange(B, dtype=jnp.uint32) % jnp.uint32(AUDIT_CAPACITY)
    else:
        hi = jnp.arange(B, dtype=jnp.uint32) * jnp.uint32(2654435761)
        lo = jnp.arange(B, dtype=jnp.uint32)
    per = (hi, lo, jnp.zeros(B, jnp.int32), jnp.ones(B, jnp.float32),
           jnp.ones(B, bool))
    # tiered families take a trailing replicated residency mask; the
    # canonical call marks every key-group resident (the mask is data,
    # so the all-resident trace covers the divert path structurally)
    tier = ((jnp.ones(ctx.max_parallelism, bool),) if fam.tiered else ())
    if fam.kind in ("update", "combined"):
        return (state,) + per + (watermark_vector(ctx, 0),) + tier
    if fam.kind in ("megastep", "megastep_fired"):
        wmv = jnp.zeros((ctx.n_shards, fam.k_steps), jnp.int32)
        return (state,) + per * fam.k_steps + (wmv,) + tier
    if fam.kind == "resident_drain":
        # partially-filled ring (count = depth - 1): both cond branches
        # are live in the traced program, so the audit sees the gate
        wmv = jnp.zeros((ctx.n_shards, fam.k_steps), jnp.int32)
        count = jnp.asarray(fam.k_steps - 1, jnp.int32)
        return (state,) + per * fam.k_steps + (wmv, count) + tier
    if fam.kind == "chained_drain":
        # same operand shape as the single-stage resident drain: the
        # chained edge is internal to the kernel (state is the tuple)
        wmv = jnp.zeros((ctx.n_shards, fam.k_steps), jnp.int32)
        count = jnp.asarray(fam.k_steps - 1, jnp.int32)
        return (state,) + per * fam.k_steps + (wmv, count)
    if fam.kind in ("sharded_drain", "chained_drain_sharded"):
        # per-shard [n_shards, cap] lane slices (cap = the audit batch)
        # and a per-shard count VECTOR at depth - 1 — both cond
        # branches live, per-shard gating in the traced signature
        n = ctx.n_shards
        per2 = tuple(jnp.broadcast_to(a, (n,) + a.shape) for a in per)
        wmv = jnp.zeros((n, fam.k_steps), jnp.int32)
        counts = jnp.full((n,), fam.k_steps - 1, jnp.int32)
        return (state,) + per2 * fam.k_steps + (wmv, counts) + tier
    if fam.kind == "while_drain":
        # cursor = base + (depth - 1) staged slots: the while_loop's
        # bound is live (not the static depth), so the traced program
        # keeps the cursor re-read in its condition
        wmv = jnp.zeros((ctx.n_shards, fam.k_steps), jnp.int32)
        cursor = jnp.full((1,), fam.k_steps - 1, jnp.int32)
        base = jnp.asarray(0, jnp.int32)
        staged = jnp.asarray(fam.k_steps - 1, jnp.int32)
        return ((state,) + per * fam.k_steps
                + (wmv, cursor, base, staged) + tier)
    if fam.kind == "while_drain_sharded":
        # per-shard cursor/base/staged VECTORS — each shard trips its
        # own while_loop on its own publish cursor
        n = ctx.n_shards
        per2 = tuple(jnp.broadcast_to(a, (n,) + a.shape) for a in per)
        wmv = jnp.zeros((n, fam.k_steps), jnp.int32)
        cursor = jnp.full((n,), fam.k_steps - 1, jnp.int32)
        base = jnp.zeros((n,), jnp.int32)
        staged = jnp.full((n,), fam.k_steps - 1, jnp.int32)
        return ((state,) + per2 * fam.k_steps
                + (wmv, cursor, base, staged) + tier)
    if fam.kind == "dcn_resident":
        # [depth, B] slot-major stacks + per-shard wm columns / done /
        # fills (fills = depth - 1: both cond branches live)
        D = fam.k_steps
        n = ctx.n_shards
        stack = tuple(jnp.broadcast_to(a, (D,) + a.shape) for a in per)
        wm = jnp.zeros((D, n), jnp.int32)
        done = jnp.zeros((n,), jnp.int32)
        fills = jnp.full((n,), D - 1, jnp.int32)
        return (state,) + stack + (wm, done, fills)
    if fam.kind in ("fire", "fire_reduced"):
        return (state, watermark_vector(ctx, 0))
    if fam.kind == "session":
        # (state, hi, lo, ts, values, valid, per-shard watermark)
        return (state,) + per + (jnp.zeros(ctx.n_shards, jnp.int32),)
    if fam.kind in ("count", "rolling"):
        # (state, hi, lo, values, valid) — no event-time operands
        return (state, per[0], per[1], per[3], per[4])
    return (state,)


def build_family(fam: KernelFamily, ctx: MeshContext,
                 batch: int = AUDIT_BATCH):
    """Instantiate one canonical family: ``(fn, example_args,
    donate_argnums)``. ``fn`` is exactly what the executor would hold
    (the exchange route's plain wrapper keeps its jitted inner on
    ``.jit`` for AOT consumers); ``example_args`` is a concrete call the
    auditor can make_jaxpr / lower / compile against."""
    spec = audit_stage_spec(fam)
    kw = {}
    if fam.kind in ("update", "megastep", "megastep_fired",
                    "resident_drain", "sharded_drain", "chained_drain",
                    "chained_drain_sharded", "while_drain",
                    "while_drain_sharded"):
        kw["insert"] = fam.insert
        kw["kg_fill"] = True
    if fam.route == "exchange":
        kw["batch_per_device"] = batch
    if fam.kind in ("megastep", "megastep_fired"):
        kw["k_steps"] = fam.k_steps
    if fam.kind in ("megastep_fired", "resident_drain", "sharded_drain"):
        kw["reduced"] = fam.reduced
    if fam.kind in ("resident_drain", "sharded_drain"):
        kw["depth"] = fam.k_steps
        kw["drain_stats"] = fam.drain_stats
    if fam.kind in ("update", "megastep", "megastep_fired",
                    "resident_drain", "sharded_drain"):
        kw["tiered"] = fam.tiered
    if fam.kind in ("chained_drain", "chained_drain_sharded"):
        kw["depth"] = fam.k_steps
        kw["exchange_lanes"] = AUDIT_EXCHANGE_LANES
        kw["drain_stats"] = fam.drain_stats
    if fam.kind in ("while_drain", "while_drain_sharded"):
        kw["max_slots"] = fam.k_steps
        kw["reduced"] = fam.reduced
        kw["drain_stats"] = fam.drain_stats
        kw["tiered"] = fam.tiered
    if fam.kind == "dcn_resident":
        kw["depth"] = fam.k_steps
        kw["insert"] = fam.insert
        kw["drain_stats"] = fam.drain_stats
    fn = fam.builder(ctx, spec, **kw)
    init = {
        "session": init_session_state,
        "count": init_count_state,
        "rolling": init_rolling_state,
    }.get(fam.kind, init_sharded_state)
    if fam.kind in ("chained_drain", "chained_drain_sharded"):
        state = tuple(init_sharded_state(ctx, s) for s in spec)
    else:
        state = init(ctx, spec)
    args = _family_example_args(fam, ctx, state, batch)
    return fn, args, ((0,) if fam.donated else ())
