"""Sources — batched, replayable, checkpointable.

Contract redesign of the reference's SourceFunction (run(SourceContext) on a
dedicated thread, emitting under the checkpoint lock — SURVEY §2.5) for a
micro-batch world:

    poll(max_records) -> (elements | columns, end_of_stream)
    snapshot_offsets() / restore_offsets(state)   — exactly-once replay
                                                   (FlinkKafkaConsumerBase
                                                   offset pattern, §2.8)

Offsets snapshot at step boundaries (the barrier), so restore + replay
reproduces the exact same micro-batches — the TPU analog of barrier-aligned
exactly-once.

Two data modes: object mode (list of Python elements, general API) and
columnar mode (dict of numpy arrays + timestamps, the fast path).
"""

from __future__ import annotations

import socket
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class Source:
    columnar = False

    def open(self):  # lifecycle (RichFunction.open analog)
        pass

    def close(self):
        pass

    def poll(self, max_records: int):
        raise NotImplementedError

    def poll_with_offsets(self, max_records: int):
        """Poll one batch AND capture the post-poll offsets in one call:
        ``(polled, end, offsets)``. This is the unit a prefetched batch
        carries (runtime/ingest.py) — the offsets name the exact replay
        point *after* this batch, so a checkpoint that snapshots the
        offsets of the last applied batch restores without skipping or
        double-applying records, no matter how far the prefetch thread
        has polled ahead. The default composition is atomic for every
        source polled from a single thread (the ingest pipeline
        guarantees one producer); sources whose offsets can move outside
        ``poll()`` should override to make the pair atomic."""
        polled, end = self.poll(max_records)
        return polled, end, self.snapshot_offsets()

    # -- checkpointing --------------------------------------------------
    def snapshot_offsets(self):
        return None

    def restore_offsets(self, state):
        pass

    def notify_checkpoint_complete(self, checkpoint_id: int, offsets=None):
        """Called once a checkpoint containing `offsets` is durable — the
        point where offsets may be committed externally (ref
        FlinkKafkaConsumerBase.notifyCheckpointComplete:384)."""


class CollectionSource(Source):
    """from_collection: finite in-memory source with replayable position."""

    def __init__(self, elements: List[Any]):
        self.elements = list(elements)
        self.pos = 0

    def poll(self, max_records: int):
        chunk = self.elements[self.pos : self.pos + max_records]
        self.pos += len(chunk)
        return chunk, self.pos >= len(self.elements)

    def snapshot_offsets(self):
        return self.pos

    def restore_offsets(self, state):
        self.pos = int(state)


class ColumnarSource(Source):
    """Base for the fast path: poll returns (columns dict, ts_ms array, end)."""

    columnar = True


class GeneratorSource(ColumnarSource):
    """Deterministic replayable generator: fn(offset, n) -> (columns, ts_ms).

    The Kafka-analog used by benchmarks: offset-addressable, infinite or
    bounded, exactly-once via offset snapshot/restore.
    """

    def __init__(self, fn, total: Optional[int] = None):
        self.fn = fn
        self.total = total
        self.offset = 0

    def poll(self, max_records: int):
        n = max_records
        if self.total is not None:
            n = min(n, self.total - self.offset)
        if n <= 0:
            return ({}, None), True
        cols, ts = self.fn(self.offset, n)
        self.offset += n
        end = self.total is not None and self.offset >= self.total
        return (cols, ts), end

    def snapshot_offsets(self):
        return self.offset

    def restore_offsets(self, state):
        self.offset = int(state)


class RingBufferSource(ColumnarSource):
    """Drains the native C++ ingestion ring (flink_tpu.native.RingBuffer)
    into the columnar fast path — the DCN ingestion front-end replacing the
    reference's Netty server + record deserializer (SURVEY §2.10). A
    producer thread/process pushes framed batches; poll() surfaces them as
    {key_id, value} columns + timestamps with zero per-record Python work.

    Not offset-replayable (the ring is transient, like a socket); pair with
    an upstream replayable system for exactly-once, or accept at-least-once
    on restore like the reference's socket source."""

    def __init__(self, ring=None, capacity: int = 1 << 22,
                 shm_name: Optional[str] = None, stop_when_idle: bool = False,
                 shm_create: Optional[bool] = None):
        """shm_create: True = initialize the named segment (producer-owner
        role), False = attach to an existing one (consumer role; never
        resets a live producer's ring), None = attach if it exists, else
        create."""
        from flink_tpu.native import RingBuffer

        self._owns_ring = ring is None
        if ring is not None:
            self.ring = ring
        elif shm_name is None:
            self.ring = RingBuffer(capacity)
        elif shm_create is None:
            # race-safe attach-or-create: exclusive create wins atomically
            # or fails because the segment exists, in which case attach —
            # retrying briefly in case the creator is still mid-init
            # (magic is published last). Never resets a live producer's
            # ring: this path has no owner-create fallback.
            try:
                self.ring = RingBuffer(capacity, name=shm_name,
                                       create="exclusive")
            except OSError:
                last = None
                for _ in range(50):
                    try:
                        self.ring = RingBuffer(capacity, name=shm_name,
                                               create=False)
                        break
                    except OSError as e:
                        last = e
                        time.sleep(0.01)
                else:
                    raise OSError(
                        f"ring {shm_name!r} exists but never became "
                        f"initialized"
                    ) from last
        else:
            self.ring = RingBuffer(capacity, name=shm_name, create=shm_create)
        self.stop_when_idle = stop_when_idle
        self._ended = False

    def end_of_stream(self):
        """Producer-side signal: drain remaining batches, then stop."""
        self._ended = True

    def poll(self, max_records: int):
        # snapshot the end flag BEFORE draining: the producer writes its
        # final batches and THEN signals, so anything written before the
        # signal is visible to this drain — no final-batch race
        ended_before = self._ended
        keys_l, ts_l, vals_l = [], [], []
        n = 0
        while n < max_records:
            batch = self.ring.read_batch()
            if batch is None:
                break
            k, t, v = batch
            keys_l.append(k)
            ts_l.append(t)
            vals_l.append(v)
            n += len(k)
        if not keys_l:
            end = ended_before or self.stop_when_idle
            return ({}, None), end
        keys = np.concatenate(keys_l)
        ts = np.concatenate(ts_l)
        vals = np.concatenate(vals_l)
        return ({"key_id": keys, "value": vals}, ts), False

    def close(self):
        # a caller-supplied ring may still have a live producer attached
        if self._owns_ring:
            self.ring.close()


class SocketTextStreamSource(Source):
    """socketTextStream: newline-delimited text over TCP
    (ref SocketTextStreamFunction role). Non-replayable (at-most-once on
    restore), like the reference's socket source.
    """

    def __init__(self, host: str, port: int, connect_timeout_s: float = 10.0):
        self.host, self.port = host, port
        self.connect_timeout_s = connect_timeout_s
        self._sock = None
        self._buf = b""
        self._eof = False

    def open(self):
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s
        )
        self._sock.setblocking(False)

    def close(self):
        if self._sock:
            self._sock.close()

    def poll(self, max_records: int):
        if self._eof and not self._buf:
            return [], True
        if not self._eof:
            try:
                while True:
                    data = self._sock.recv(1 << 16)
                    if not data:
                        self._eof = True
                        break
                    self._buf += data
                    if self._buf.count(b"\n") >= max_records:
                        break
            except (BlockingIOError, socket.timeout):
                pass
        lines = []
        while len(lines) < max_records and b"\n" in self._buf:
            line, self._buf = self._buf.split(b"\n", 1)
            lines.append(line.decode("utf-8", errors="replace"))
        # EOF flush covers ONLY a trailing unterminated line — a buffer
        # still holding newline-terminated lines (EOF arrived while more
        # than max_records lines were buffered) keeps draining on
        # subsequent polls, one line per record, instead of being
        # emitted as one mega-"line"
        if self._eof and self._buf and b"\n" not in self._buf \
                and len(lines) < max_records:
            lines.append(self._buf.decode("utf-8", errors="replace"))
            self._buf = b""
        return lines, self._eof and not self._buf


class FileTextSource(Source):
    """readTextFile: line-by-line file source with byte-offset replay."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self._f = None

    def open(self):
        self._f = open(self.path, "rb")
        self._f.seek(self.offset)

    def close(self):
        if self._f:
            self._f.close()

    def poll(self, max_records: int):
        lines = []
        for _ in range(max_records):
            line = self._f.readline()
            if not line:
                return lines, True
            lines.append(line.decode("utf-8", errors="replace").rstrip("\n"))
        self.offset = self._f.tell()
        return lines, False

    def snapshot_offsets(self):
        return self._f.tell() if self._f else self.offset

    def restore_offsets(self, state):
        self.offset = int(state)
        if self._f:
            self._f.seek(self.offset)


class SocketWordsSource(ColumnarSource):
    """Columnar socket word ingestion: "<ts_ms> word word ..." lines
    parsed by the NATIVE one-pass tokenizer (native/src/textparse.cpp)
    into 64-bit token identities — the SocketWindowWordCount ingest
    path (ref SocketWindowWordCount.java:76-79) without a per-line
    Python flatMap. Keys are FNV-1a 64 token ids (stable across runs
    and processes); ``word_of(id)`` materializes the string, recorded
    once per first-seen token. Non-replayable like the socket text
    source (at-most-once on restore, the reference's socket contract).
    """

    def __init__(self, host: str, port: int,
                 connect_timeout_s: float = 10.0):
        self.host, self.port = host, port
        self.connect_timeout_s = connect_timeout_s
        self._sock = None
        self._buf = b""
        self._eof = False
        self._words = {}          # id (int) -> word str
        # (ts, ids) tail of a single line wider than one poll's cap:
        # parse_ts_words is line-atomic, so the overflow splits across
        # SUBSEQUENT polls here — the poll contract (<= max_records)
        # holds even for pathological lines
        self._carry = None

    def open(self):
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout_s
        )
        self._sock.setblocking(False)

    def close(self):
        if self._sock:
            self._sock.close()

    def word_of(self, key_id: int) -> Optional[str]:
        """The token string behind a key id (None if never seen). Accepts
        the signed int64 view result rows carry."""
        return self._words.get(int(key_id) & 0xFFFFFFFFFFFFFFFF)

    def poll(self, max_records: int):
        from flink_tpu.native import parse_ts_words

        # serve a carried oversized-line tail FIRST: its words are
        # already recorded, and mixing it with fresh lines could exceed
        # the cap again
        if self._carry is not None:
            ts_c, ids_c = self._carry
            take = min(int(max_records), len(ids_c))
            ts, ids = ts_c[:take], ids_c[:take]
            self._carry = (
                (ts_c[take:], ids_c[take:]) if take < len(ids_c) else None
            )
            cols = {
                "key": ids.view(np.int64),
                "value": np.ones(len(ids), np.float32),
                "ts": ts,
            }
            done = (
                self._carry is None and self._eof and not self._buf
            )
            return (cols, ts), done
        if not self._eof:
            try:
                while True:
                    data = self._sock.recv(1 << 18)
                    if not data:
                        self._eof = True
                        break
                    self._buf += data
                    if len(self._buf) >= max_records * 2:
                        break    # enough bytes for a full batch
            except (BlockingIOError, socket.timeout):
                pass
        data = self._buf
        if self._eof and data and not data.endswith(b"\n"):
            data += b"\n"        # flush the final unterminated line
        # cap honors the poll contract: the non-chunking keyed stage
        # paths pad to exactly B lanes, so an oversized return would
        # break them; unconsumed lines re-offer next poll
        ts, ids, offs, lens, consumed = parse_ts_words(
            data, cap=max_records
        )
        if self._eof and consumed < len(data) and len(ids) == 0:
            consumed = len(data)     # nothing parseable remains
        self._buf = self._buf[min(consumed, len(self._buf)):]
        # first-seen tokens: record their strings for word_of() — BEFORE
        # any cap split, while ``data`` (which offs/lens index) is here
        if len(ids):
            uniq, first = np.unique(ids, return_index=True)
            for u, i in zip(uniq.tolist(), first.tolist()):
                if u not in self._words:
                    o, l = int(offs[i]), int(lens[i])
                    self._words[u] = data[o:o + l].decode(
                        "utf-8", errors="replace"
                    )
        if len(ids) > max_records:
            # ONE line wider than the cap came back whole (line-atomic
            # parse); split it across polls so the contract holds.
            # Copies: the tail must not pin the parse buffers.
            self._carry = (
                ts[max_records:].copy(), ids[max_records:].copy()
            )
            ts, ids = ts[:max_records], ids[:max_records]
        cols = {
            "key": ids.view(np.int64),
            "value": np.ones(len(ids), np.float32),
            "ts": ts,    # for assign_timestamps_and_watermarks(c["ts"])
        }
        done = self._eof and not self._buf and self._carry is None
        return (cols, ts), done
