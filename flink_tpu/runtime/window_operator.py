"""Generic window operator — full WindowOperator.java semantics on the host.

Mirrors the reference's runtime/operators/windowing/WindowOperator.java
(SURVEY §2.5: processElement:222 window assignment + windowState.add +
trigger consult + cleanup-timer registration; onEventTime:337 /
onProcessingTime:378 fire path; MergingWindowSet for session merging;
EvictingWindowOperator's ListState buffering when an evictor is attached).

Role in this framework: the **generality path**. The device window kernels
(ops/window_kernels.py) execute the default trigger semantics for the hot
aligned-window aggregations; any stage with a custom Trigger, an Evictor, a
raw-elements window function (apply), or a GlobalWindows assigner routes
here, running as a ProcessFunction over the heap keyed backend + internal
timer service — which also gives it checkpoint/restore and restart recovery
for free through the process-stage machinery.
"""

from __future__ import annotations

from collections import namedtuple
from typing import Any, Callable, List, Optional

from flink_tpu.datastream.functions import Collector, ProcessFunction
from flink_tpu.datastream.window.triggers import Trigger, TriggerResult
from flink_tpu.datastream.window.windows import GlobalWindow, TimeWindow
from flink_tpu.state.backend import AggregatingState
from flink_tpu.state.descriptors import (
    AggregatingStateDescriptor,
    ListStateDescriptor,
    MapStateDescriptor,
    ReducingStateDescriptor,
)

WindowResult = namedtuple("WindowResult", ["key", "window_end_ms", "value"])
SessionResult = namedtuple(
    "SessionResult", ["key", "window_start_ms", "window_end_ms", "value"]
)


class TriggerContext:
    """Trigger.TriggerContext: window-namespaced timers + partitioned state
    (ref WindowOperator.Context)."""

    def __init__(self, operator: "GenericWindowOperator"):
        self._op = operator
        self.window = None
        self.key = None
        # windows being merged away, set only during Trigger.on_merge
        self.merged_windows = ()

    @property
    def current_watermark(self) -> int:
        return self._op._timers.current_watermark

    @property
    def current_processing_time(self) -> int:
        return self._op._timers.current_processing_time

    def register_event_time_timer(self, ts: int):
        self._op._timers.register_event_time_timer(self.window, self.key, ts)

    def register_processing_time_timer(self, ts: int):
        self._op._timers.register_processing_time_timer(
            self.window, self.key, ts)

    def delete_event_time_timer(self, ts: int):
        self._op._timers.delete_event_time_timer(self.window, self.key, ts)

    def delete_processing_time_timer(self, ts: int):
        self._op._timers.delete_processing_time_timer(
            self.window, self.key, ts)

    def get_partitioned_state(self, descriptor):
        return self._op._backend.get_partitioned_state(
            descriptor, namespace=("trig", self.window))

    def merge_partitioned_state(self, descriptor):
        """Fold the merged-away windows' per-window trigger state into the
        result window's namespace (ref Trigger.OnMergeContext.
        mergePartitionedState -> AbstractKeyedStateBackend.
        mergePartitionedStates:294). Supported for mergeable state kinds:
        reducing (combine) and list (concatenate)."""
        target = self.get_partitioned_state(descriptor)
        for w in self.merged_windows:
            if w == self.window:
                continue
            src = self._op._backend.get_partitioned_state(
                descriptor, namespace=("trig", w))
            if isinstance(descriptor, ReducingStateDescriptor):
                v = src.get()
                if v is not None:
                    if descriptor.kind == "count":
                        cur = target.get()
                        target._put(v if cur is None else cur + v)
                    else:
                        target.add(v)
            elif isinstance(descriptor, ListStateDescriptor):
                for item in src.get():
                    target.add(item)
            else:
                raise TypeError(
                    f"{type(descriptor).__name__} state is not mergeable"
                )
            src.clear()


class MergingWindowSet:
    """Session-window merge bookkeeping (ref MergingWindowSet.java): maps
    in-flight windows to the namespace ('state window') their contents live
    under, so merges re-point mappings instead of copying state."""

    def __init__(self, mapping_state):
        self._state = mapping_state  # MapState: window -> state window

    def state_window(self, window):
        return self._state.get(window)

    def retire_window(self, window):
        self._state.remove(window)

    def add_window(self, new_window, merge_cb):
        """Returns the (possibly merged) actual window for new_window.

        merge_cb(merged, merged_windows, state_window, merged_state_windows)
        is invoked when a merge happens, BEFORE mappings are updated —
        exactly the reference's MergeFunction contract.
        """
        mapping = dict(self._state.items())
        overlapping = [w for w in mapping if w.intersects(new_window)]
        if not overlapping:
            self._state.put(new_window, new_window)
            return new_window
        merged = new_window
        for w in overlapping:
            merged = merged.cover(w)
        state_windows = [mapping[w] for w in overlapping]
        keep_state = state_windows[0]
        if len(overlapping) == 1 and overlapping[0] == merged:
            return merged  # fully contained, nothing changes
        merge_cb(merged, overlapping, keep_state, state_windows[1:])
        for w in overlapping:
            self._state.remove(w)
        self._state.put(merged, keep_state)
        return merged


class GenericWindowOperator(ProcessFunction):
    def __init__(
        self,
        assigner,
        trigger: Optional[Trigger] = None,
        evictor=None,
        extractor: Callable = None,
        reduce_desc: Optional[ReducingStateDescriptor] = None,
        window_fn: Optional[Callable] = None,  # (key, window, elements)->iter
        allowed_lateness_ms: int = 0,
        result_fn: Optional[Callable] = None,
    ):
        self.assigner = assigner
        self.trigger = trigger or assigner.default_trigger()
        self.evictor = evictor
        self.extractor = extractor or (lambda e: e)
        self.reduce_desc = reduce_desc
        self.window_fn = window_fn
        self.lateness = allowed_lateness_ms
        self.result_fn = result_fn
        # evictors and raw-element window functions need the full buffer
        # (EvictingWindowOperator ListState path)
        self.buffered = evictor is not None or (
            window_fn is not None and reduce_desc is None
        )
        self.dropped_late = 0
        self.fires = 0

    # -- wiring (called by the process-stage executor) -------------------
    def bind_internals(self, backend, timers):
        self._backend = backend
        self._timers = timers

    def open(self, runtime_ctx):
        self._rt = runtime_ctx
        self._trigger_ctx = TriggerContext(self)
        if self.buffered:
            self._contents_desc = ListStateDescriptor("window-contents")
        else:
            self._contents_desc = self.reduce_desc
        self._merge_desc = MapStateDescriptor("merging-window-set")

    # -- helpers ----------------------------------------------------------
    def _window_state(self, window):
        return self._backend.get_partitioned_state(
            self._contents_desc, namespace=("win", window))

    def _cleanup_time(self, window) -> int:
        if isinstance(window, GlobalWindow):
            return window.max_timestamp()
        if self.assigner.is_event_time:
            t = window.max_timestamp() + self.lateness
            return t if t >= window.max_timestamp() else 2**62
        return window.max_timestamp()

    def _register_cleanup(self, key, window):
        t = self._cleanup_time(window)
        if t >= 2**62:
            return
        if self.assigner.is_event_time:
            self._timers.register_event_time_timer(window, key, t)
        else:
            self._timers.register_processing_time_timer(window, key, t)

    def _delete_cleanup(self, key, window):
        t = self._cleanup_time(window)
        if t >= 2**62:
            return
        if self.assigner.is_event_time:
            self._timers.delete_event_time_timer(window, key, t)
        else:
            self._timers.delete_processing_time_timer(window, key, t)

    def _is_window_late(self, window) -> bool:
        return (
            self.assigner.is_event_time
            and not isinstance(window, GlobalWindow)
            and self._cleanup_time(window) <= self._timers.current_watermark
        )

    def _emit(self, key, window, value, out: Collector):
        self.fires += 1
        if self.result_fn is not None:
            value = self.result_fn(value)
        if isinstance(window, GlobalWindow):
            out.collect(WindowResult(key, None, value))
        elif self.assigner.is_merging:
            out.collect(SessionResult(key, window.start, window.end, value))
        else:
            out.collect(WindowResult(key, window.end, value))

    def _fire(self, key, window, out: Collector, state_window=None):
        """Evaluate + emit one window. For merging (session) windows the
        contents live under `state_window`'s namespace; otherwise it is the
        window itself."""
        state = self._window_state(state_window or window)
        if self.buffered:
            elements = list(state.get())
            n = len(elements)
            if self.evictor is not None:
                elements = self.evictor.evict_before(elements, n, window)
            if not elements:
                return
            if self.window_fn is not None:
                self.fires += 1
                for r in self.window_fn(key, window,
                                        [v for v, _ in elements]):
                    out.collect(r)
            elif isinstance(self.reduce_desc, AggregatingStateDescriptor):
                acc = self.reduce_desc.create_accumulator()
                for v, _ in elements:
                    acc = self.reduce_desc.add(acc, v)
                if self.reduce_desc.get_result is not None:
                    acc = self.reduce_desc.get_result(acc)
                self._emit(key, window, acc, out)
            elif self.reduce_desc is not None:
                acc = elements[0][0]
                for v, _ in elements[1:]:
                    acc = self.reduce_desc.host_reduce(acc, v)
                self._emit(key, window, acc, out)
            else:
                self._emit(key, window, [v for v, _ in elements], out)
            if self.evictor is not None:
                retained = self.evictor.evict_after(
                    elements, len(elements), window)
                state.update(retained)
        else:
            acc = state.get()
            if acc is None:
                return
            if self.window_fn is not None:
                self.fires += 1
                for r in self.window_fn(key, window, [acc]):
                    out.collect(r)
            else:
                self._emit(key, window, acc, out)

    def _clear_window(self, key, window, merging_set=None):
        state_window = window
        if merging_set is not None:
            sw = merging_set.state_window(window)
            if sw is not None:
                state_window = sw
            merging_set.retire_window(window)
        self._window_state(state_window).clear()
        self._trigger_ctx.window = window
        self._trigger_ctx.key = key
        self.trigger.clear(window, self._trigger_ctx)

    # -- ProcessFunction hooks --------------------------------------------
    def process_element(self, element, ctx, out):
        key = self._backend.current_key
        ts = ctx.timestamp()
        value = self.extractor(element)
        windows = self.assigner.assign_windows(ts)

        if self.assigner.is_merging:
            self._process_merging(key, element, value, ts, windows, out)
            return

        all_late = True
        for window in windows:
            if self._is_window_late(window):
                continue
            all_late = False
            state = self._window_state(window)
            if self.buffered:
                state.add((value, ts))
            else:
                state.add(value)
            self._trigger_ctx.window = window
            self._trigger_ctx.key = key
            r = self.trigger.on_element(element, ts, window, self._trigger_ctx)
            if r.is_fire:
                self._fire(key, window, out)
            if r.is_purge:
                self._window_state(window).clear()
            self._register_cleanup(key, window)
        if all_late and windows:
            self.dropped_late += 1

    def _process_merging(self, key, element, value, ts, windows, out):
        merging_set = MergingWindowSet(
            self._backend.get_partitioned_state(self._merge_desc))

        for window in windows:
            def merge_cb(merged, merged_windows, keep_state, drop_states,
                         _key=key):
                # merge window contents into the kept state window
                target = self._window_state(keep_state)
                for sw in drop_states:
                    src = self._window_state(sw)
                    if self.buffered:
                        for item in src.get():
                            target.add(item)
                    elif isinstance(target, AggregatingState):
                        a = src.get_accumulator()
                        if a is not None:
                            target.merge_accumulator(
                                a, self._contents_desc.merge)
                    else:
                        v = src.get()
                        if v is not None:
                            target.add(v)
                    src.clear()
                # trigger.onMerge FIRST (may merge per-window trigger state
                # out of the dying windows), THEN clear those windows — the
                # reference's WindowOperator merge callback order; the kept
                # window (when it equals the merge result) is never cleared
                self._trigger_ctx.window = merged
                self._trigger_ctx.key = _key
                if self.trigger.can_merge():
                    self._trigger_ctx.merged_windows = merged_windows
                    self.trigger.on_merge(merged, self._trigger_ctx)
                    self._trigger_ctx.merged_windows = ()
                for w in merged_windows:
                    if w == merged:
                        continue
                    self._trigger_ctx.window = w
                    self._trigger_ctx.key = _key
                    self.trigger.clear(w, self._trigger_ctx)
                    self._delete_cleanup(_key, w)

            actual = merging_set.add_window(window, merge_cb)
            if self._is_window_late(actual):
                merging_set.retire_window(actual)
                self.dropped_late += 1
                continue
            state_window = merging_set.state_window(actual) or actual
            state = self._window_state(state_window)
            if self.buffered:
                state.add((value, ts))
            else:
                state.add(value)
            self._trigger_ctx.window = actual
            self._trigger_ctx.key = key
            r = self.trigger.on_element(element, ts, actual, self._trigger_ctx)
            if r.is_fire:
                self._fire(key, actual, out, state_window=state_window)
            if r.is_purge:
                state.clear()
            self._register_cleanup(key, actual)

    def on_timer(self, timestamp, ctx, out):
        key = ctx.get_current_key()
        window = ctx.namespace
        if window is None:
            return
        merging_set = None
        state_window = window
        if self.assigner.is_merging:
            merging_set = MergingWindowSet(
                self._backend.get_partitioned_state(self._merge_desc))
            sw = merging_set.state_window(window)
            if sw is None:
                return  # window was merged away; its timers are stale
            state_window = sw

        self._trigger_ctx.window = window
        self._trigger_ctx.key = key
        if ctx.time_domain == "event":
            r = self.trigger.on_event_time(timestamp, window,
                                           self._trigger_ctx)
        else:
            r = self.trigger.on_processing_time(timestamp, window,
                                                self._trigger_ctx)
        if r.is_fire:
            self._fire(key, window, out, state_window=state_window)
        if r.is_purge:
            self._window_state(state_window).clear()

        if timestamp == self._cleanup_time(window) and not isinstance(
                window, GlobalWindow):
            self._clear_window(key, window, merging_set)
