"""Queryable state — external point lookups into live keyed state.

The reference runs a dedicated Netty KvState server per TaskManager with
location lookup through the JobManager (SURVEY §2.2: KvStateRegistry /
QueryableStateClient / KvStateServerHandler). Here the registry lives on
the environment, stages register read closures over their LIVE state
(device arrays for compiled stages — reads snapshot the current array
without pausing the job; heap tables for the generality path), and the web
monitor serves lookups over HTTP:

    GET /jobs/<jid>/state/<name>?key=<k>

QueryableStateClient wraps that endpoint (the reference client's
getKvState role).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Optional


class KvStateRegistry:
    def __init__(self):
        self._fns: Dict[str, Callable[[Any], Any]] = {}
        # (names_fn, query_fn) pairs resolving states created lazily after
        # registration time (e.g. a ValueState first touched mid-stream —
        # the heap backend only knows its name once a record creates it)
        self._resolvers = []
        self._lock = threading.Lock()

    def register(self, name: str, fn: Callable[[Any], Any]):
        with self._lock:
            self._fns[name] = fn

    def register_resolver(self, names_fn: Callable[[], list],
                          query_fn: Callable[[str, Any], Any]):
        with self._lock:
            self._resolvers.append((names_fn, query_fn))

    def names(self):
        with self._lock:
            out = set(self._fns)
            resolvers = list(self._resolvers)
        for names_fn, _ in resolvers:
            out.update(names_fn())
        return sorted(out)

    def query(self, name: str, key):
        with self._lock:
            fn = self._fns.get(name)
            resolvers = list(self._resolvers)
        if fn is not None:
            return fn(key)
        for names_fn, query_fn in resolvers:
            if name in names_fn():
                return query_fn(name, key)
        raise KeyError(f"no queryable state named {name!r}")


def parse_key(raw: str):
    """HTTP query keys arrive as strings; recover numerics (the client
    sends typed keys as their repr)."""
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        return raw


class QueryableStateClient:
    """ref QueryableStateClient: point lookups against a running job.
    Attaches the shared secret (runtime/security.py) as a Bearer token
    when one is configured — the server side 401s without it."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0,
                 token: Optional[str] = None):
        from flink_tpu.runtime import security

        self.base = f"http://{host}:{port}"
        self.timeout_s = timeout_s
        self.token = token if token is not None else security.get_token()

    def get_kv_state(self, job_id: str, name: str, key) -> Any:
        q = urllib.parse.quote(str(key))
        url = f"{self.base}/jobs/{job_id}/state/{name}?key={q}"
        req = urllib.request.Request(url)
        if self.token is not None:
            req.add_header("Authorization", f"Bearer {self.token}")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            payload = json.loads(r.read())
        if not payload.get("ok", False):
            raise KeyError(payload.get("error", "state query failed"))
        return payload["value"]
