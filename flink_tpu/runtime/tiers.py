"""Tiered key-group state: HBM-resident hot set over a host cold tier.

Every key of a job used to live in HBM, capping key cardinality per chip
at device memory — the opposite of a millions-of-users profile (huge
cold tail, small hot working set). This module is the host half of the
tier (ISSUE 18): a ``TierManager`` owns the per-shard residency mask
(``state.tiers.resident-key-groups`` budgets how many key-groups sit in
HBM per shard), ranks groups by the flight recorder's EWMA heat +
recency series (ISSUE 17) plus the watermark-derived next-fire pane,
and plans demote/promote swaps the executor applies at the
exactly-once cut between drains.

The device half is one extra operand, not a new kernel: tiered step
families take a replicated ``kg_res`` bool[max_parallelism] mask and
divert lanes of non-resident groups down the existing overflow ring
(``ops/window_kernels.update``), so a batch routing into a cold group
falls down the route ladder for that batch only — never lossy, counted
in the ``tier_faults`` gauge. Residency is *data*, not structure: the
compiled families stay shape-stable as the mask changes.

Correctness is invariant to residency: a group's pending contributions
live either in device slot rows or in the host pane ``SpillStore``s,
and both halves feed the same logical (key, pane, value) entry format
at fire, checkpoint, and restore. Demote/promote merely move entries
between the halves (see ``partition_entries`` / ``fold_entries`` /
``ring_window``), which is why a crash between a demote and its
checkpoint replays cleanly — the restored cut re-seeds both tiers from
the same logical snapshot. ``docs/state-tiers.md`` carries the full
argument.

Everything here is plain host numpy on already-fetched telemetry — the
manager never touches device buffers and adds zero dispatches to the
hot path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from flink_tpu.testing import faults

# score bonus that puts a group with an imminent window fire ahead of
# any heat ranking: the prefetcher MUST have it resident before the
# fire so the emission comes off the device instead of a host merge
_FIRE_BOOST = 1e18


@dataclass(frozen=True)
class TierPlan:
    """One maintenance decision: groups to demote and promote, applied
    together at the next exactly-once cut. ``prefetch`` marks the
    subset of ``promote`` chosen predictively (watermark next-fire or
    heat ranking) rather than reactively (observed faults)."""

    demote: List[int] = field(default_factory=list)
    promote: List[int] = field(default_factory=list)
    prefetch: Set[int] = field(default_factory=set)

    def __bool__(self):
        return bool(self.demote or self.promote)


class TierManager:
    """Host-side residency policy + cold-tier index for one window stage.

    The executor consults it at poll-cycle boundaries (the same seam
    the elastic re-plan latch uses): feed it sampled kg-fill telemetry
    (``note_sample``), the ring->store merge stream (``note_cold``),
    and the flight recorder's heat/recency series (``plan``); apply the
    returned :class:`TierPlan` via the executor's demote/promote splice
    and confirm with :meth:`apply`.
    """

    def __init__(self, max_parallelism: int, starts: Sequence[int],
                 ends: Sequence[int], budget: int,
                 prefetch_ahead_panes: int = 2,
                 min_dwell_cycles: int = 4,
                 max_swaps_per_cycle: int = 0):
        if budget <= 0:
            raise ValueError("tier budget must be positive "
                             "(0 disables tiering upstream)")
        self.maxp = int(max_parallelism)
        self.budget = int(budget)
        self.prefetch_ahead_panes = int(prefetch_ahead_panes)
        self.min_dwell_cycles = int(min_dwell_cycles)
        # cap on promote+demote moves one plan may return
        # (state.tiers.max-swaps-per-cycle; 0 = unlimited): swap work
        # runs at the poll-cycle seam on the step loop, so a working-set
        # shift bigger than the cap carries forward instead of stalling
        # one cycle behind a giant splice burst
        self.max_swaps_per_cycle = int(max_swaps_per_cycle)
        self.resident = np.zeros(self.maxp, bool)
        self._shard_of = np.zeros(self.maxp, np.int32)
        self._cycle = 0
        self._last_flip = np.full(self.maxp, -(10 ** 9), np.int64)
        # cold-tier index: per-group earliest pane with pending spill
        # entries (the watermark prefetch signal) + approximate entry
        # count (ranking/evidence only — the stores stay authoritative)
        self._pending_pane: Dict[int, int] = {}
        self._cold_count: Dict[int, int] = {}
        # groups promoted predictively, awaiting their first observed
        # traffic (resolves to a prefetch hit) or eviction (a miss)
        self._prefetched: Set[int] = set()
        # counters surfaced as Prometheus gauges / pipeline block
        self.tier_faults = 0
        self.demotes = 0
        self.promotes = 0
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.rescale(starts, ends)

    # ------------------------------------------------------------ setup

    def rescale(self, starts: Sequence[int], ends: Sequence[int],
                budget: Optional[int] = None):
        """(Re-)slice residency for new shard ranges — initial setup,
        elastic re-plan, and the live savepoint-cut rescale all land
        here. The first ``budget`` groups of each shard's range start
        resident (cold groups earn their way in via heat); counters
        survive, the per-range dwell clocks reset."""
        if budget is not None:
            self.budget = int(budget)
        starts = np.asarray(starts, np.int64)
        ends = np.asarray(ends, np.int64)
        self.starts, self.ends = starts, ends
        self.resident[:] = False
        for s in range(len(starts)):
            lo = int(starts[s])
            hi = min(int(ends[s]), lo + self.budget - 1)
            self.resident[lo:hi + 1] = True
            self._shard_of[lo:int(ends[s]) + 1] = s
        self._last_flip[:] = -(10 ** 9)
        self._prefetched.clear()

    # ------------------------------------------------------------ index

    def mask(self) -> np.ndarray:
        """The residency mask the executor stages as the kernels'
        ``kg_res`` operand (a copy — the manager keeps mutating its
        own)."""
        return self.resident.copy()

    def resident_groups(self) -> int:
        return int(self.resident.sum())

    def shard_of(self, kg: int) -> int:
        """Owning shard of a key-group under the current ranges."""
        return int(self._shard_of[int(kg)])

    def note_cold(self, kgs: np.ndarray, panes: np.ndarray):
        """Ring->store merge stream: lanes of these key-groups just
        landed in the host pane stores. Maintains the earliest-pending-
        pane index the watermark prefetcher ranks on. Resident groups
        appear here too (plain capacity overflow) — they index as well,
        so a promote of a formerly-cold group also reclaims any
        overflow residue."""
        kgs = np.asarray(kgs)
        panes = np.asarray(panes)
        for g in np.unique(kgs):
            sel = kgs == g
            p = int(panes[sel].min())
            g = int(g)
            cur = self._pending_pane.get(g)
            self._pending_pane[g] = p if cur is None else min(cur, p)
            self._cold_count[g] = self._cold_count.get(g, 0) + int(
                sel.sum()
            )

    def forget_cold(self, kg: int):
        """A promote (or store prune) moved this group's pending
        entries out of the cold tier."""
        self._pending_pane.pop(int(kg), None)
        self._cold_count.pop(int(kg), None)

    def prune_cold(self, cutoff_pane: int):
        """Pane stores at or below ``cutoff_pane`` were pruned (every
        containing window fired) — drop index entries that pointed only
        there."""
        for g in [g for g, p in self._pending_pane.items()
                  if p <= cutoff_pane]:
            self.forget_cold(g)

    def note_sample(self, kg_sum: np.ndarray):
        """One sampled per-group fill vector (the lagged overflow-
        pressure fetch): batches observed routing into non-resident
        groups are tier faults; first observed traffic on a
        predictively-promoted group resolves its prefetch to a hit.
        Sampled, so the gauges are rates-of-samples, not exact counts —
        documented in docs/state-tiers.md."""
        kg_sum = np.asarray(kg_sum)
        n = min(kg_sum.size, self.maxp)
        hot = np.nonzero(kg_sum[:n] > 0)[0]
        if not len(hot):
            return
        faulted = hot[~self.resident[hot]]
        self.tier_faults += int(len(faulted))
        for g in hot:
            if int(g) in self._prefetched:
                self._prefetched.discard(int(g))
                self.prefetch_hits += 1

    # ------------------------------------------------------------- plan

    def plan(self, heat: np.ndarray, last_seen: np.ndarray, seq: int,
             wm_pane: Optional[int] = None) -> TierPlan:
        """Rank every group and swap toward the per-shard budget.

        ``heat``/``last_seen``/``seq`` are the flight recorder's EWMA
        kg-heat plane, last-traffic sequence numbers, and current
        sequence (DrainTelemetry, ISSUE 17). ``wm_pane`` is the current
        watermark pane: any cold group with pending spill entries in a
        pane at or below ``wm_pane + prefetch-ahead-panes`` is about to
        fire and outranks everything (the timely-prefetch condition —
        watermark progression makes the next touch predictable).
        Hysteresis: a group that flipped within ``min_dwell_cycles``
        stays put, except for an imminent-fire promote."""
        self._cycle += 1
        heat = np.asarray(heat, np.float64)
        last_seen = np.asarray(last_seen, np.int64)
        score = np.zeros(self.maxp, np.float64)
        n = min(heat.size, self.maxp)
        score[:n] = heat[:n]
        # recency: groups seen recently get a decaying bonus scaled to
        # the heat plane, so a just-touched cold group outranks an
        # equally-warm long-idle one
        if n:
            seen = last_seen[:n] >= 0
            age = np.maximum(0, seq - last_seen[:n])
            scale = max(1.0, float(heat[:n].max(initial=0.0)))
            score[:n][seen] += scale / (1.0 + age[seen])
        urgent: Set[int] = set()
        if wm_pane is not None:
            horizon = wm_pane + self.prefetch_ahead_panes
            for g, p in self._pending_pane.items():
                if p <= horizon and not self.resident[g]:
                    score[g] += _FIRE_BOOST
                    urgent.add(g)

        demote: List[int] = []
        promote: List[int] = []
        prefetch: Set[int] = set()
        dwell_ok = (
            self._cycle - self._last_flip >= self.min_dwell_cycles
        )
        # swap budget across BOTH move kinds and all shards; a plan the
        # cap truncates leaves the residue un-flipped (no _last_flip
        # stamp), so the next cycle's ranking re-derives and carries it
        # forward
        swaps_left = (
            self.max_swaps_per_cycle if self.max_swaps_per_cycle > 0
            else 2 * self.maxp + 1
        )
        for s in range(len(self.starts)):
            lo, hi = int(self.starts[s]), int(self.ends[s])
            if lo > hi:
                continue
            rng = np.arange(lo, hi + 1)
            res = self.resident[rng]
            sc = score[rng]
            # desired residents: the budget top-scored groups of the
            # range; ties broken toward the incumbents (stability)
            order = np.argsort(-(sc + 1e-9 * res), kind="stable")
            want = np.zeros(len(rng), bool)
            want[order[: self.budget]] = True
            demoted_here = 0
            for i in np.nonzero(res & ~want)[0]:
                if swaps_left <= 0:
                    break
                g = int(rng[i])
                if dwell_ok[g]:
                    demote.append(g)
                    demoted_here += 1
                    swaps_left -= 1
            # promotions fill exactly the slots the demotes freed (plus
            # any initial slack), so residency never exceeds the budget
            # — a capped demote pass shrinks the room with it
            room = self.budget - (int(res.sum()) - demoted_here)
            for i in order:
                if room <= 0 or swaps_left <= 0:
                    break
                if want[i] and not res[i]:
                    g = int(rng[i])
                    if dwell_ok[g] or g in urgent:
                        promote.append(g)
                        room -= 1
                        swaps_left -= 1
                        if g in urgent or self._cold_count.get(g, 0) == 0:
                            prefetch.add(g)
        return TierPlan(demote=demote, promote=promote, prefetch=prefetch)

    def apply(self, plan: TierPlan):
        """The executor finished the device/store swap for ``plan`` —
        commit the mask flips, dwell clocks, and counters."""
        for g in plan.demote:
            self.resident[g] = False
            self._last_flip[g] = self._cycle
            if g in self._prefetched:
                # predicted, never touched, already evicted: a miss
                self._prefetched.discard(g)
                self.prefetch_misses += 1
        for g in plan.promote:
            self.resident[g] = True
            self._last_flip[g] = self._cycle
            if g in plan.prefetch:
                self._prefetched.add(g)
        self.demotes += len(plan.demote)
        self.promotes += len(plan.promote)

    # ------------------------------------------------------- reporting

    def report(self) -> dict:
        """The ``tiers`` block for ``/jobs/<jid>/pipeline`` and the
        doctor's snapshot."""
        pending = sorted(self._pending_pane.items())
        return {
            "budget_per_shard": self.budget,
            "resident_groups": self.resident_groups(),
            "cold_groups_pending": len(self._pending_pane),
            "cold_entries_approx": int(sum(self._cold_count.values())),
            "next_pending_pane": pending[0][1] if pending else None,
            "faults": self.tier_faults,
            "demotes": self.demotes,
            "promotes": self.promotes,
            "prefetch_hits": self.prefetch_hits,
            "prefetch_misses": self.prefetch_misses,
        }


# ------------------------------------------------- entry-plane helpers
#
# Demote/promote move logical (key, pane, value) entries between the
# device rows and the host pane stores. These helpers are the pure host
# halves the executor composes with its stage/restore/splice machinery.


def entries_key_groups(entries: dict, max_parallelism: int) -> np.ndarray:
    """Key-group of every logical entry (the same route hash the device
    uses, run in host numpy)."""
    from flink_tpu.ops.window_kernels import (assign_to_key_group,
                                              route_hash)

    return assign_to_key_group(
        route_hash(entries["key_hi"], entries["key_lo"], np),
        max_parallelism, np,
    )


def split_entries(entries: dict, keep: np.ndarray):
    """Partition one entry dict by a boolean mask -> (kept, dropped)."""

    def take(m):
        return {k: np.asarray(v)[m] for k, v in entries.items()}

    keep = np.asarray(keep, bool)
    return take(keep), take(~keep)


def fold_entries(entries: dict, stores: dict, width: int, ufunc,
                 neutral, make_store, combine,
                 fault_point: Optional[str] = "tier.demote.write"):
    """Demote write: fold logical entries into the per-pane host
    stores, pre-combined per (key, pane) with the stage's reduce.
    ``make_store`` lazily creates a store for a new pane; ``combine``
    merges with an existing stored block. Runs behind the
    ``tier.demote.write`` fault seam — a crash here loses only host
    memory the next restore re-seeds from the last cut. Internal
    re-folds (the off-ring half of a promote going straight back)
    pass ``fault_point=None``: they are not a demote IO boundary."""
    n = len(entries["pane"])
    if fault_point is not None:
        faults.inject(fault_point, entries=n)
    if not n:
        return
    k64 = (
        entries["key_hi"].astype(np.uint64) << np.uint64(32)
    ) | entries["key_lo"].astype(np.uint64)
    panes = entries["pane"]
    vals = entries["value"].reshape(n, width).astype(np.float32)
    for p in np.unique(panes):
        sel = panes == p
        uk, inv = np.unique(k64[sel], return_inverse=True)
        agg = np.full((len(uk), width), neutral, np.float32)
        ufunc.at(agg, inv, vals[sel])
        store = stores.get(int(p))
        if store is None:
            store = stores[int(p)] = make_store()
        old, found = store.get(uk)
        merged = np.where(found[:, None], combine(old, agg), agg)
        store.put(uk, merged)


def fetch_group_entries(stores: dict, kg: int, max_parallelism: int,
                        width: int, value_tail, value_dtype):
    """Promote read: pull every pending entry of key-group ``kg`` out
    of the pane stores (get + delete — after this the device copy is
    authoritative). Returns an entry dict in the logical snapshot
    format. Runs behind the ``tier.promote.read`` fault seam."""
    from flink_tpu.ops.window_kernels import (assign_to_key_group,
                                              route_hash)

    faults.inject("tier.promote.read", kg=int(kg))
    khi_l, klo_l, pane_l, val_l = [], [], [], []
    for p, store in list(stores.items()):
        if len(store) == 0:
            continue
        ks, vs = store.dump()
        hi = (ks >> np.uint64(32)).astype(np.uint32)
        lo = (ks & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        mine = assign_to_key_group(
            route_hash(hi, lo, np), max_parallelism, np
        ) == kg
        if not mine.any():
            continue
        store.delete(ks[mine])
        khi_l.append(hi[mine])
        klo_l.append(lo[mine])
        pane_l.append(np.full(int(mine.sum()), int(p), np.int32))
        val_l.append(vs[mine])
    if not khi_l:
        return {
            "key_hi": np.zeros(0, np.uint32),
            "key_lo": np.zeros(0, np.uint32),
            "pane": np.zeros(0, np.int32),
            "value": np.zeros((0,) + tuple(value_tail), value_dtype),
            "fresh": np.zeros(0, bool),
        }
    value = np.concatenate(val_l).reshape(-1, width)
    if not value_tail:
        value = value[:, 0]
    return {
        "key_hi": np.concatenate(khi_l),
        "key_lo": np.concatenate(klo_l),
        "pane": np.concatenate(pane_l),
        "value": value.astype(value_dtype),
        # promoted entries re-enter the device as fresh pending state:
        # their windows have not fired yet (fired panes were pruned)
        "fresh": np.ones(sum(len(a) for a in khi_l), bool),
    }


def concat_entries(a: dict, b: dict) -> dict:
    """Union two entry dicts (the kept device half + the promoted store
    half). (key, pane) duplicates are legal — the caller pre-combines
    with the stage reduce before the last-write-wins restore scatter."""
    return {
        k: np.concatenate([np.asarray(a[k]), np.asarray(b[k])])
        for k in a
    }


def precombine_entries(entries: dict, width: int, ufunc, neutral) -> dict:
    """Collapse (key, pane) duplicates with the stage's reduce so the
    restore scatter (last-write-wins) sees each logical cell once. A
    key's pending state can split across device and store when the
    table filled mid-pane; the union re-joins it here."""
    n = len(entries["pane"])
    if not n:
        return entries
    k64 = (
        entries["key_hi"].astype(np.uint64) << np.uint64(32)
    ) | entries["key_lo"].astype(np.uint64)
    cell = (k64, entries["pane"].astype(np.int64))
    uniq, inv = np.unique(np.stack(
        [cell[0].astype(np.int64), cell[1]], axis=1
    ), axis=0, return_inverse=True)
    if len(uniq) == n:
        return entries
    vals = entries["value"].reshape(n, width).astype(np.float32)
    agg = np.full((len(uniq), width), neutral, np.float32)
    ufunc.at(agg, inv, vals)
    fresh = np.zeros(len(uniq), bool)
    np.logical_or.at(fresh, inv, entries["fresh"].astype(bool))
    tail = entries["value"].shape[1:]
    # the int64 view of the u64 key is bijective — cast back to recover
    uk = uniq[:, 0].astype(np.uint64)
    return {
        "key_hi": (uk >> np.uint64(32)).astype(np.uint32),
        "key_lo": (uk & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        "pane": uniq[:, 1].astype(np.int32),
        "value": agg.reshape((len(uniq),) + tuple(tail)).astype(
            entries["value"].dtype
        ),
        "fresh": fresh,
    }


def ring_window(entries: dict, max_pane: int, ring: int):
    """Split entries into (on-ring, off-ring) halves for a promote: only
    panes inside the live ring window can splice onto the device; the
    rest stay in the cold tier and merge at fire the normal way. A
    silent drop here would be data loss — the caller folds the off-ring
    half straight back into the stores."""
    from flink_tpu.ops.window_kernels import PANE_NONE

    pane = entries["pane"]
    if max_pane == int(PANE_NONE):
        # no pane has ever landed on the device ring: nothing can splice
        return split_entries(entries, np.zeros(len(pane), bool))
    fits = (pane > max_pane - ring) & (pane <= max_pane)
    return split_entries(entries, fits)
