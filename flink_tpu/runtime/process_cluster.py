"""ProcessCluster — controller for real multi-process workers.

The first step toward the reference's distributed runtime story
(VERDICT item 10): the controller plays the JobManager role for worker
OS processes — spawn, registration, heartbeat liveness (the Akka
DeathWatch analog: a worker is dead on heartbeat timeout OR process
exit, TaskManager.scala:296 / ExecutionGraph.java:848), and
restart-from-latest-checkpoint when a worker dies mid-job, governed by a
fixed-delay restart budget (restart/FixedDelayRestartStrategy.java:33).

Control traffic rides the same JSON-over-TCP line protocol the CLI uses
(cluster.py); bulk data between local processes rides the native shm
ring (runtime/sources.RingBufferSource) — neither path depends on being
in one process.
"""

from __future__ import annotations

import json
import os
import socketserver
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class WorkerRecord:
    worker_id: str
    proc: subprocess.Popen
    job_name: str
    builder_ref: str
    checkpoint_dir: str
    attempt: int = 1
    status: str = "LAUNCHED"   # LAUNCHED|REGISTERED|RUNNING|FINISHED|FAILED|DEAD
    last_heartbeat: float = field(default_factory=time.time)
    error: Optional[str] = None
    restarts: int = 0
    extra_env: Optional[dict] = None


class ProcessCluster:
    """Controller process: spawn/monitor worker processes, recover jobs."""

    def __init__(self, heartbeat_timeout_s: float = 3.0,
                 max_restarts: int = 3, monitor_interval_s: float = 0.25,
                 startup_grace_s: float = 60.0):
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.max_restarts = max_restarts
        self.monitor_interval_s = monitor_interval_s
        # a LAUNCHED worker is importing the framework (several seconds);
        # the heartbeat liveness contract starts once it registers
        self.startup_grace_s = startup_grace_s
        self.workers: Dict[str, WorkerRecord] = {}
        self._lock = threading.Lock()
        self._server = None
        self._port: Optional[int] = None
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.events: List[dict] = []    # observable lifecycle log

    # -- control server ---------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        cluster = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                line = self.rfile.readline()
                if not line:
                    return
                try:
                    resp = cluster._dispatch(json.loads(line))
                except Exception as e:
                    resp = {"ok": False, "error": str(e)}
                self.wfile.write(
                    (json.dumps(resp, default=str) + "\n").encode()
                )

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="process-cluster-control",
        ).start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="process-cluster-monitor",
        )
        self._monitor.start()
        return self._port

    def shutdown(self):
        self._stop.set()
        with self._lock:
            recs = list(self.workers.values())
        for rec in recs:
            if rec.proc.poll() is None:
                rec.proc.kill()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def _event(self, kind: str, **kw):
        self.events.append({"event": kind, "t": time.time(), **kw})

    def _dispatch(self, req: dict) -> dict:
        action = req.get("action")
        if action == "register-worker":
            with self._lock:
                rec = self.workers.get(req["worker_id"])
                if rec is not None:
                    rec.status = "REGISTERED"
                    rec.last_heartbeat = time.time()
            self._event("registered", worker=req["worker_id"],
                        pid=req.get("pid"))
            return {"ok": True}
        if action == "heartbeat":
            with self._lock:
                rec = self.workers.get(req["worker_id"])
                if rec is not None:
                    rec.last_heartbeat = time.time()
                    if rec.status == "REGISTERED":
                        rec.status = "RUNNING"
            return {"ok": True}
        if action == "worker-status":
            with self._lock:
                rec = self.workers.get(req["worker_id"])
                if rec is not None:
                    rec.status = req["status"]
                    rec.error = req.get("error")
            self._event("status", worker=req["worker_id"],
                        status=req["status"])
            return {"ok": True}
        if action == "list":
            with self._lock:
                return {"ok": True, "workers": [
                    {"worker_id": r.worker_id, "status": r.status,
                     "attempt": r.attempt, "restarts": r.restarts}
                    for r in self.workers.values()
                ]}
        raise ValueError(f"unknown action {action!r}")

    # -- job lifecycle ----------------------------------------------------
    def submit(self, builder_ref: str, job_name: str,
               checkpoint_dir: str, worker_id: Optional[str] = None,
               extra_env: Optional[dict] = None) -> str:
        worker_id = worker_id or f"worker-{len(self.workers) + 1:03d}"
        rec = WorkerRecord(
            worker_id=worker_id,
            proc=self._spawn(worker_id, builder_ref, job_name,
                             checkpoint_dir, restore=False,
                             extra_env=extra_env),
            job_name=job_name, builder_ref=builder_ref,
            checkpoint_dir=checkpoint_dir, extra_env=extra_env,
        )
        with self._lock:
            self.workers[worker_id] = rec
        self._event("launched", worker=worker_id, attempt=1)
        return worker_id

    def _spawn(self, worker_id: str, builder_ref: str, job_name: str,
               checkpoint_dir: str, restore: bool,
               extra_env: Optional[dict] = None) -> subprocess.Popen:
        cmd = [
            sys.executable, "-m", "flink_tpu.runtime.worker",
            "--controller", str(self._port),
            "--worker-id", worker_id,
            "--builder", builder_ref,
            "--job-name", job_name,
            "--checkpoint-dir", checkpoint_dir,
        ]
        if restore:
            cmd.append("--restore")
        env = dict(os.environ)
        if extra_env:
            env.update(extra_env)
        # worker output goes to a per-worker log (the TaskManager .log /
        # .out files of the reference's bin scripts)
        log = subprocess.DEVNULL
        if checkpoint_dir:
            os.makedirs(checkpoint_dir, exist_ok=True)
            log = open(
                os.path.join(checkpoint_dir, f"{worker_id}.log"), "ab"
            )
        return subprocess.Popen(cmd, env=env, stdout=log, stderr=log)

    # -- DeathWatch + restart ---------------------------------------------
    def _monitor_loop(self):
        while not self._stop.wait(self.monitor_interval_s):
            now = time.time()
            with self._lock:
                recs = list(self.workers.values())
            for rec in recs:
                if rec.status in ("FINISHED", "FAILED", "DEAD"):
                    continue
                exited = rec.proc.poll() is not None
                timeout = (
                    self.startup_grace_s if rec.status == "LAUNCHED"
                    else self.heartbeat_timeout_s
                )
                stale = now - rec.last_heartbeat > timeout
                if not (exited or stale):
                    continue
                # the worker may have exited cleanly right after its
                # terminal status message raced in — re-check
                with self._lock:
                    if rec.status in ("FINISHED", "FAILED"):
                        continue
                    cause = "exit" if exited else "heartbeat-timeout"
                    self._event("death", worker=rec.worker_id, cause=cause,
                                attempt=rec.attempt)
                    if rec.proc.poll() is None:
                        rec.proc.kill()
                    if rec.restarts >= self.max_restarts:
                        rec.status = "DEAD"
                        self._event("gave-up", worker=rec.worker_id)
                        continue
                    rec.restarts += 1
                    rec.attempt += 1
                    rec.status = "LAUNCHED"
                    rec.last_heartbeat = time.time()
                    rec.proc = self._spawn(
                        rec.worker_id, rec.builder_ref, rec.job_name,
                        rec.checkpoint_dir, restore=True,
                        extra_env=rec.extra_env,
                    )
                    self._event("restarted", worker=rec.worker_id,
                                attempt=rec.attempt)

    def wait(self, worker_id: str, timeout_s: float = 120.0) -> str:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                rec = self.workers[worker_id]
                if rec.status in ("FINISHED", "FAILED", "DEAD"):
                    return rec.status
            time.sleep(0.1)
        raise TimeoutError(
            f"worker {worker_id} still {rec.status} after {timeout_s}s"
        )

    def kill_worker(self, worker_id: str):
        """Test hook: SIGKILL the worker process (fault injection, ref
        ProcessFailureCancelingITCase-style recovery tests)."""
        with self._lock:
            rec = self.workers[worker_id]
        rec.proc.kill()
