"""ProcessCluster — controller for real multi-process workers.

The controller plays the JobManager role for worker OS processes —
spawn, registration, heartbeat liveness (the Akka DeathWatch analog: a
worker is dead on heartbeat timeout OR process exit,
TaskManager.scala:296 / ExecutionGraph.java:848), and
restart-from-latest-checkpoint when a worker dies mid-job, governed by a
fixed-delay restart budget (restart/FixedDelayRestartStrategy.java:33).

Control traffic rides the same JSON-over-TCP line protocol the CLI uses
(cluster.py); bulk data between local processes rides the native shm
ring (runtime/sources.RingBufferSource) — neither path depends on being
in one process. Workers are addressed to ``advertise_host:port`` and the
server can bind 0.0.0.0, so controller and workers need not share a host
(TaskManager.scala:296 network registration).

High availability (ref ZooKeeperLeaderElectionService.java:47 +
ZooKeeperSubmittedJobGraphStore): with ``ha_dir`` set, serving is gated
on leadership (``runtime/ha.FileLeaderElection`` flock) and every
submitted job is durably recorded in the ``HAJobRegistry``. Worker
processes are bound to their leader's lifetime via PR_SET_PDEATHSIG (the
per-job-container pattern: a task lease dies with the master that
granted it, like the reference's TM task cancellation on JM loss), so a
standby that wins the lock recovers every RUNNING job from its latest
durable checkpoint. Run a standalone controller with
``python -m flink_tpu.runtime.process_cluster --ha-dir DIR``.
"""

from __future__ import annotations

import ctypes
import json
import os
import signal as _signal
import socketserver
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from flink_tpu.runtime.ha import (
    FileLeaderElection,
    HAJobRegistry,
    StandaloneLeaderElection,
    leader_info,
)


# resolved at import: preexec_fn runs between fork and exec, where a
# dlopen/malloc in the child of a multithreaded parent can deadlock on
# loader/allocator locks another thread held at fork time
try:
    _LIBC = ctypes.CDLL("libc.so.6", use_errno=True)
except OSError:           # non-glibc platform: workers outlive a dead leader
    _LIBC = None


def _die_with_parent():
    """preexec_fn: deliver SIGKILL to the child when the thread that
    forked it (the long-lived spawner) dies — PR_SET_PDEATHSIG(1)."""
    if _LIBC is not None:
        _LIBC.prctl(1, _signal.SIGKILL)


@dataclass
class WorkerRecord:
    worker_id: str
    proc: Optional[subprocess.Popen]   # None while (re)spawn is in flight
    job_name: str
    builder_ref: str
    checkpoint_dir: str
    attempt: int = 1
    status: str = "LAUNCHED"   # LAUNCHED|REGISTERED|RUNNING|FINISHED|FAILED|DEAD
    last_heartbeat: float = field(default_factory=time.time)
    error: Optional[str] = None
    restarts: int = 0
    extra_env: Optional[dict] = None
    # True for workers the controller did NOT spawn: independently
    # launched TaskManagers (bin/taskmanager.sh on another host) that
    # registered themselves — tracked and death-watched, never respawned
    external: bool = False


class ProcessCluster:
    """Controller process: spawn/monitor worker processes, recover jobs."""

    def __init__(self, heartbeat_timeout_s: float = 3.0,
                 max_restarts: int = 3, monitor_interval_s: float = 0.25,
                 startup_grace_s: float = 60.0,
                 ha_dir: Optional[str] = None,
                 contender_id: Optional[str] = None,
                 advertise_host: str = "127.0.0.1",
                 auth_token: Optional[str] = None):
        # explicit token wins; else the FLINK_TPU_AUTH_TOKEN[_FILE]
        # environment resolves (runtime/security.py); None = open cluster
        self.auth_token = auth_token
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.max_restarts = max_restarts
        self.monitor_interval_s = monitor_interval_s
        # a LAUNCHED worker is importing the framework (several seconds);
        # the heartbeat liveness contract starts once it registers
        self.startup_grace_s = startup_grace_s
        self.advertise_host = advertise_host
        self.workers: Dict[str, WorkerRecord] = {}
        self._worker_seq = 0
        self._lock = threading.Lock()
        self._server = None
        self._port: Optional[int] = None
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.events: List[dict] = []    # observable lifecycle log
        self.ha_dir = ha_dir
        self.registry = HAJobRegistry(ha_dir) if ha_dir else None
        self.election = (
            FileLeaderElection(ha_dir, contender_id or f"ctl-{os.getpid()}")
            if ha_dir else StandaloneLeaderElection()
        )
        self.leadership = threading.Event()
        self.failed = threading.Event()    # leadership won but serving died
        # all forks run on one long-lived spawner thread, whose lifetime
        # is the controller's — see runtime/spawner.py for why (PDEATHSIG
        # thread semantics + the abandoned-request claim protocol)
        from flink_tpu.runtime.spawner import AbandonableSpawner

        self._spawner = AbandonableSpawner("process-cluster-spawner")

    def _spawn(self, *args, **kw) -> subprocess.Popen:
        return self._spawner.submit(
            lambda: self._spawn_inner(*args, **kw),
            on_abandon=lambda proc: proc.kill(),
        )

    # -- control server ---------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0,
              block_for_leadership_s: Optional[float] = None):
        """Contend for leadership; serve once granted.

        Without ``ha_dir`` leadership is standalone (granted synchronously,
        ref StandaloneLeaderElectionService) and the bound port is
        returned, preserving the single-controller API. With ``ha_dir``
        this returns immediately (a standby blocks on the leader lock in a
        background thread); pass ``block_for_leadership_s`` to wait.
        """

        def on_grant():
            # a failure here must not wedge the cluster: the flock is
            # already held, so release it (election.stop) before dying so
            # another standby can take over
            try:
                self._start_serving(host, port)
                if self.ha_dir:
                    self.election.publish({
                        "host": self.advertise_host, "port": self._port,
                        "pid": os.getpid(),
                    })
                self._event("leadership-granted", port=self._port)
                if self.registry is not None:
                    self._recover_jobs()
            except Exception as e:
                self._event("leadership-failed", error=str(e))
                self.failed.set()
                self.election.stop()
                raise
            self.leadership.set()

        self.election.start(on_grant)
        if block_for_leadership_s is not None:
            if not self.leadership.wait(block_for_leadership_s):
                raise TimeoutError("leadership not granted in time")
        return self._port

    def _start_serving(self, host: str, port: int):
        from flink_tpu.runtime import security

        cluster = self
        token = self.auth_token or security.get_token()

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                line = self.rfile.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    # authenticate BEFORE dispatch: an unauthenticated
                    # caller cannot submit/cancel/register
                    # (SecurityContext.java:53 analog, runtime/security.py)
                    security.check(token, req)
                    resp = cluster._dispatch(req)
                except Exception as e:
                    resp = {"ok": False, "error": str(e)}
                self.wfile.write(
                    (json.dumps(resp, default=str) + "\n").encode()
                )

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="process-cluster-control",
        ).start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="process-cluster-monitor",
        )
        self._monitor.start()
        return self._port

    def _recover_jobs(self):
        """Leader takeover: respawn every RUNNING job in the HA registry
        from its latest durable checkpoint (the previous leader's workers
        died with it via PDEATHSIG). Ref: new JobManager leader recovering
        the SubmittedJobGraphStore + completed-checkpoint store."""
        for worker_id, rec in self.registry.all().items():
            if rec.get("status") != "RUNNING":
                continue
            # insert the record BEFORE spawning (as submit() does): the
            # worker can register the instant it forks, and an unknown id
            # at that moment would be mis-adopted as an external worker
            wrec = WorkerRecord(
                worker_id=worker_id, proc=None,
                job_name=rec["job_name"], builder_ref=rec["builder_ref"],
                checkpoint_dir=rec["checkpoint_dir"],
                extra_env=rec.get("extra_env"),
            )
            with self._lock:
                self.workers[worker_id] = wrec
            try:
                proc = self._spawn(worker_id, rec["builder_ref"],
                                   rec["job_name"], rec["checkpoint_dir"],
                                   restore=True,
                                   extra_env=rec.get("extra_env"))
            except Exception as e:  # one bad job must not block the rest
                self._event("recover-failed", worker=worker_id,
                            error=str(e))
                with self._lock:
                    wrec.status = "FAILED"
                    wrec.error = str(e)
                self.registry.update_status(worker_id, "FAILED")
                continue
            with self._lock:
                wrec.proc = proc
            self._event("recovered", worker=worker_id)

    def shutdown(self):
        self._stop.set()
        self.election.stop()
        self._spawner.stop()
        with self._lock:
            recs = list(self.workers.values())
        for rec in recs:
            if rec.proc is not None and rec.proc.poll() is None:
                rec.proc.kill()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def _event(self, kind: str, **kw):
        self.events.append({"event": kind, "t": time.time(), **kw})

    def _dispatch(self, req: dict) -> dict:
        action = req.get("action")
        if action == "register-worker":
            with self._lock:
                rec = self.workers.get(req["worker_id"])
                adopted = rec is None
                if rec is not None:
                    # re-registration revives even a DEAD external record:
                    # the worker proving liveness IS the revival signal
                    # (its transient network gap is over)
                    rec.status = "REGISTERED"
                    rec.last_heartbeat = time.time()
                    external = rec.external
                else:
                    # ADOPT an independently launched worker — the
                    # reference's TaskManager-registers-itself flow
                    # (TaskManager.scala:296): it appears in the worker
                    # list, heartbeats drive its status, and the
                    # DeathWatch flags it DEAD on silence (it cannot be
                    # respawned — its process belongs to another host)
                    self.workers[req["worker_id"]] = WorkerRecord(
                        worker_id=req["worker_id"], proc=None,
                        job_name=req.get("job_name", ""),
                        builder_ref=req.get("builder", ""),
                        checkpoint_dir=req.get("checkpoint_dir", ""),
                        status="REGISTERED", external=True,
                    )
                    external = True
            self._event("registered", worker=req["worker_id"],
                        pid=req.get("pid"), external=external,
                        adopted=adopted)
            return {"ok": True}
        if action == "heartbeat":
            with self._lock:
                rec = self.workers.get(req["worker_id"])
                if rec is not None:
                    rec.last_heartbeat = time.time()
                    if rec.status == "REGISTERED" or (
                        rec.external and rec.status == "DEAD"
                    ):
                        # an external record flagged DEAD by a transient
                        # heartbeat gap revives on the next beat — the
                        # worker never stopped, only its signal did
                        rec.status = "RUNNING"
            return {"ok": True}
        if action == "worker-status":
            with self._lock:
                rec = self.workers.get(req["worker_id"])
                if rec is not None:
                    rec.status = req["status"]
                    rec.error = req.get("error")
            if self.registry is not None and req["status"] in (
                "FINISHED", "FAILED"
            ):
                self.registry.update_status(req["worker_id"], req["status"])
            self._event("status", worker=req["worker_id"],
                        status=req["status"])
            return {"ok": True}
        if action == "submit":
            wid = self.submit(
                req["builder"], req.get("job_name", "job"),
                req.get("checkpoint_dir", ""),
                worker_id=req.get("worker_id"),
                extra_env=req.get("extra_env"),
            )
            return {"ok": True, "worker_id": wid}
        if action == "list":
            with self._lock:
                return {"ok": True, "workers": [
                    {"worker_id": r.worker_id, "status": r.status,
                     "attempt": r.attempt, "restarts": r.restarts}
                    for r in self.workers.values()
                ]}
        raise ValueError(f"unknown action {action!r}")

    # -- job lifecycle ----------------------------------------------------
    def submit(self, builder_ref: str, job_name: str,
               checkpoint_dir: str, worker_id: Optional[str] = None,
               extra_env: Optional[dict] = None) -> str:
        # reserve the id under the lock BEFORE the (slow, unlocked) spawn:
        # concurrent submits over the control server must neither collide
        # on generated ids nor silently overwrite a record (which would
        # orphan the first worker process)
        rec = WorkerRecord(
            worker_id="", proc=None, status="SPAWNING",
            job_name=job_name, builder_ref=builder_ref,
            checkpoint_dir=checkpoint_dir, extra_env=extra_env,
        )
        with self._lock:
            if worker_id is None:
                # skip ids already taken — e.g. HA-recovered workers keep
                # their original ids but the new leader's counter restarts
                while True:
                    self._worker_seq += 1
                    worker_id = f"worker-{self._worker_seq:03d}"
                    if worker_id not in self.workers:
                        break
            elif worker_id in self.workers:
                raise ValueError(f"worker id {worker_id!r} already exists")
            rec.worker_id = worker_id
            self.workers[worker_id] = rec
        try:
            proc = self._spawn(worker_id, builder_ref, job_name,
                               checkpoint_dir, restore=False,
                               extra_env=extra_env)
        except Exception:
            with self._lock:
                self.workers.pop(worker_id, None)
            raise
        with self._lock:
            rec.proc = proc
            if rec.status == "SPAWNING":   # it may already have registered
                rec.status = "LAUNCHED"
            rec.last_heartbeat = time.time()
        if self.registry is not None:
            self.registry.put(worker_id, {
                "builder_ref": builder_ref, "job_name": job_name,
                "checkpoint_dir": checkpoint_dir, "extra_env": extra_env,
                "status": "RUNNING",
            })
        self._event("launched", worker=worker_id, attempt=1)
        return worker_id

    def _spawn_inner(self, worker_id: str, builder_ref: str, job_name: str,
                     checkpoint_dir: str, restore: bool,
                     extra_env: Optional[dict] = None) -> subprocess.Popen:
        cmd = [
            sys.executable, "-m", "flink_tpu.runtime.worker",
            "--controller", f"{self.advertise_host}:{self._port}",
            "--worker-id", worker_id,
            "--builder", builder_ref,
            "--job-name", job_name,
            "--checkpoint-dir", checkpoint_dir,
        ]
        if restore:
            cmd.append("--restore")
        env = dict(os.environ)
        if self.auth_token:
            # an explicitly-passed token must reach spawned workers too
            # (they authenticate via control_request's env lookup)
            from flink_tpu.runtime import security

            env[security.ENV_TOKEN] = self.auth_token
        if extra_env:
            env.update(extra_env)
        # worker output goes to a per-worker log (the TaskManager .log /
        # .out files of the reference's bin scripts)
        log = subprocess.DEVNULL
        if checkpoint_dir:
            os.makedirs(checkpoint_dir, exist_ok=True)
            log = open(
                os.path.join(checkpoint_dir, f"{worker_id}.log"), "ab"
            )
        # the task lease dies with the controller that granted it: a new
        # HA leader recovers from the checkpoint, never fights a zombie
        return subprocess.Popen(cmd, env=env, stdout=log, stderr=log,
                                preexec_fn=_die_with_parent)

    # -- DeathWatch + restart ---------------------------------------------
    def _monitor_loop(self):
        while not self._stop.wait(self.monitor_interval_s):
            now = time.time()
            with self._lock:
                recs = list(self.workers.values())
            to_respawn = []
            for rec in recs:
                if rec.status in ("FINISHED", "FAILED", "DEAD",
                                  "SPAWNING", "RESPAWNING"):
                    continue
                if rec.external:
                    # adopted worker: heartbeat silence is the only death
                    # signal, and there is no process to respawn (a later
                    # heartbeat/re-registration revives the record)
                    if now - rec.last_heartbeat > self.heartbeat_timeout_s:
                        with self._lock:
                            # re-check under the lock: a beat may have
                            # landed since the unlocked staleness read
                            if (
                                rec.status in ("FINISHED", "FAILED")
                                or time.time() - rec.last_heartbeat
                                <= self.heartbeat_timeout_s
                            ):
                                continue
                            rec.status = "DEAD"
                        self._event("death", worker=rec.worker_id,
                                    cause="heartbeat-timeout",
                                    external=True)
                    continue
                if rec.proc is None:     # spawn still in flight
                    continue
                exited = rec.proc.poll() is not None
                timeout = (
                    self.startup_grace_s if rec.status == "LAUNCHED"
                    else self.heartbeat_timeout_s
                )
                stale = now - rec.last_heartbeat > timeout
                if not (exited or stale):
                    continue
                # the worker may have exited cleanly right after its
                # terminal status message raced in — re-check
                with self._lock:
                    if rec.status in ("FINISHED", "FAILED"):
                        continue
                    cause = "exit" if exited else "heartbeat-timeout"
                    self._event("death", worker=rec.worker_id, cause=cause,
                                attempt=rec.attempt)
                    if rec.proc.poll() is None:
                        rec.proc.kill()
                    if rec.restarts >= self.max_restarts:
                        rec.status = "DEAD"
                        if self.registry is not None:
                            self.registry.update_status(
                                rec.worker_id, "DEAD"
                            )
                        self._event("gave-up", worker=rec.worker_id)
                        continue
                    rec.restarts += 1
                    rec.attempt += 1
                    rec.status = "RESPAWNING"
                    rec.last_heartbeat = time.time()
                    to_respawn.append(rec)
            # fork OUTSIDE the lock: a slow spawn must not block the
            # heartbeat/register handlers (blocked heartbeats would read
            # as dead workers and cascade restarts across the cluster)
            for rec in to_respawn:
                try:
                    proc = self._spawn(
                        rec.worker_id, rec.builder_ref, rec.job_name,
                        rec.checkpoint_dir, restore=True,
                        extra_env=rec.extra_env,
                    )
                except Exception as e:
                    with self._lock:
                        rec.status = "FAILED"
                        rec.error = str(e)
                    if self.registry is not None:
                        self.registry.update_status(rec.worker_id, "FAILED")
                    self._event("restart-failed", worker=rec.worker_id,
                                error=str(e))
                    continue
                with self._lock:
                    rec.proc = proc
                    if rec.status == "RESPAWNING":
                        rec.status = "LAUNCHED"
                    rec.last_heartbeat = time.time()
                self._event("restarted", worker=rec.worker_id,
                            attempt=rec.attempt)

    def wait(self, worker_id: str, timeout_s: float = 120.0) -> str:
        with self._lock:
            if worker_id not in self.workers:
                raise ValueError(f"unknown worker {worker_id!r}; known: "
                                 f"{sorted(self.workers)}")
        deadline = time.time() + timeout_s
        while True:
            with self._lock:
                rec = self.workers[worker_id]
                if rec.status in ("FINISHED", "FAILED", "DEAD"):
                    return rec.status
            if time.time() >= deadline:
                raise TimeoutError(
                    f"worker {worker_id} still {rec.status} after {timeout_s}s"
                )
            time.sleep(0.1)

    def kill_worker(self, worker_id: str):
        """Test hook: SIGKILL the worker process (fault injection, ref
        ProcessFailureCancelingITCase-style recovery tests)."""
        with self._lock:
            rec = self.workers[worker_id]
        if rec.proc is None:
            raise RuntimeError(
                f"worker {worker_id} spawn still in flight; nothing to kill"
            )
        rec.proc.kill()


def main(argv=None) -> int:
    """Standalone controller process (the reference's jobmanager.sh):
    contend for leadership, then serve until killed. With --ha-dir a
    standby blocks on the leader lock and takes over on leader death."""
    import argparse

    from flink_tpu.core.config import load_global_configuration
    from flink_tpu.runtime import security

    # flag > conf/flink-tpu-conf.yaml > built-in default (the reference's
    # CLI-over-flink-conf.yaml precedence)
    gconf = load_global_configuration()
    ap = argparse.ArgumentParser()
    ap.add_argument("--host",
                    default=gconf.get_str("controller.bind-host",
                                          "127.0.0.1"),
                    help="bind address (0.0.0.0 for multi-host)")
    ap.add_argument("--port", type=int,
                    # lint: allow(config): ephemeral port (0), not 6123 — spawned test ensembles on one host must not collide
                    default=gconf.get_int("controller.rpc.port", 0))
    ap.add_argument("--advertise-host", default="127.0.0.1")
    ap.add_argument("--ha-dir",
                    # lint: allow(config): argparse wants a string; '' is the same standalone mode as the declared None default
                    default=gconf.get_str("high-availability.dir", "")
                    or None)
    ap.add_argument("--contender-id", default=None)
    ap.add_argument("--heartbeat-timeout-s", type=float, default=3.0)
    ap.add_argument("--max-restarts", type=int, default=3)
    a = ap.parse_args(argv)

    cluster = ProcessCluster(
        heartbeat_timeout_s=a.heartbeat_timeout_s,
        max_restarts=a.max_restarts,
        ha_dir=a.ha_dir, contender_id=a.contender_id,
        advertise_host=a.advertise_host,
        auth_token=security.get_token(gconf),
    )
    cluster.start(host=a.host, port=a.port)
    print(f"[controller {a.contender_id or os.getpid()}] contending "
          f"(ha_dir={a.ha_dir})", flush=True)
    # exit non-zero (for a supervisor to respawn) if leadership was won
    # but serving failed — never linger as a zombie standby
    while not cluster.leadership.wait(0.5):
        if cluster.failed.is_set():
            print("[controller] leadership grant failed; exiting",
                  file=sys.stderr, flush=True)
            return 1
    print(f"[controller] leading on port {cluster._port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        cluster.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
