"""Union / tagged-union merge of several source branches.

The reference implements multi-input operators by unioning the inputs and
dispatching on a tag (CoGroupedStreams' TaggedUnion + UnionSerializer;
TwoInputStreamTask reads both gates into one loop). Here the merge happens
at the micro-batch boundary: a MergedSource round-robins over the branch
sources, runs each branch's fused host chain, optionally wraps elements in
Tagged(tag, value, ts), and interleaves the results into one batch stream.

Timestamps are extracted at the position of the branch's
assign_timestamps_and_watermarks call (ops after it inherit the input
element's timestamp, as the reference's TimestampedCollector does for
flatMap), and the merged watermark is the MIN over per-branch watermarks —
the reference's two-input rule (StreamTwoInputProcessor keeps one watermark
per input and forwards the minimum); an exhausted branch contributes
MAX_WATERMARK, like the reference's end-of-input watermark emission.

Offsets snapshot/restore per branch, so exactly-once replay composes.
"""

from __future__ import annotations

import dataclasses
from collections import namedtuple
from typing import Any, Callable, List, Optional

from flink_tpu.runtime.sources import Source
from flink_tpu.runtime.watermarks import WatermarkStrategy

Tagged = namedtuple("Tagged", ["tag", "value", "ts"])
Tagged.__new__.__defaults__ = (None,)

MAX_WATERMARK_MS = 2**62


def to_elements(polled):
    """Normalize a source's poll() payload to a list of Python elements
    (columnar payloads become tuples / scalars)."""
    if (
        isinstance(polled, tuple)
        and len(polled) == 2
        and isinstance(polled[0], dict)
    ):
        cols, _ts = polled
        if not cols:
            return []
        names = list(cols)
        arrays = [cols[n] for n in names]
        if len(names) == 1:
            return list(arrays[0].tolist())
        return list(zip(*[a.tolist() for a in arrays]))
    return polled


def _apply_ops(ops, elements):
    for t in ops:
        if t.kind == "map":
            elements = [t.fn(e) for e in elements]
        elif t.kind == "filter":
            elements = [e for e in elements if t.fn(e)]
        elif t.kind == "flat_map":
            out = []
            for e in elements:
                out.extend(t.fn(e))
            elements = out
        else:
            raise NotImplementedError(t.kind)
    return elements


def _apply_ops_stamped(ops, elements, ts):
    """Chain application that threads per-element timestamps through
    cardinality changes (flat_map outputs inherit the input's timestamp)."""
    for t in ops:
        if t.kind == "map":
            elements = [t.fn(e) for e in elements]
        elif t.kind == "filter":
            kept = [(e, s) for e, s in zip(elements, ts) if t.fn(e)]
            elements = [e for e, _ in kept]
            ts = [s for _, s in kept]
        elif t.kind == "flat_map":
            out_e, out_t = [], []
            for e, s in zip(elements, ts):
                for r in t.fn(e):
                    out_e.append(r)
                    out_t.append(s)
            elements, ts = out_e, out_t
        else:
            raise NotImplementedError(t.kind)
    return elements, ts


class Branch:
    """One input of a union: a source, its host chain split around the
    timestamp assigner, and a per-branch watermark strategy."""

    def __init__(self, source, pre_ops=(), ts_fn: Optional[Callable] = None,
                 post_ops=(), strategy: Optional[WatermarkStrategy] = None,
                 tag: Optional[int] = None):
        self.source = source
        self.pre_ops = tuple(pre_ops)
        self.ts_fn = ts_fn
        self.post_ops = tuple(post_ops)
        self.strategy = (
            dataclasses.replace(strategy) if strategy is not None
            else (WatermarkStrategy() if ts_fn is not None else None)
        )
        self.tag = tag
        self.ended = False

    def poll(self, n: int) -> List[Any]:
        if self.ended:
            return []
        polled, end = self.source.poll(n)
        self.ended = end
        elements = _apply_ops(self.pre_ops, to_elements(polled))
        if self.ts_fn is None:
            elements = _apply_ops(self.post_ops, elements)
            if self.tag is not None:
                return [Tagged(self.tag, e) for e in elements]
            return elements
        ts = [int(self.ts_fn(e)) for e in elements]
        elements, ts = _apply_ops_stamped(self.post_ops, elements, ts)
        if ts:
            self.strategy.on_batch(max(ts))
        tag = self.tag if self.tag is not None else 0
        return [Tagged(tag, e, s) for e, s in zip(elements, ts)]

    def watermark(self) -> int:
        if self.ended:
            return MAX_WATERMARK_MS
        return self.strategy.current() if self.strategy else MAX_WATERMARK_MS


@dataclasses.dataclass
class MergedWatermarkStrategy(WatermarkStrategy):
    """min over per-branch watermarks, monotone non-decreasing (ref
    StreamTwoInputProcessor/StreamInputProcessor min-across-inputs merge)."""

    branches: List[Branch] = dataclasses.field(default_factory=list)

    def on_batch(self, _max_ts_ms=None) -> int:
        m = min(b.watermark() for b in self.branches)
        if m > self._current:
            self._current = m
        return self._current


class MergedSource(Source):
    """Round-robin merge of N branches behind the single-source contract."""

    columnar = False

    def __init__(self, branches: List[Branch]):
        self.branches = branches
        self._rr = 0

    def open(self):
        for b in self.branches:
            b.source.open()

    def close(self):
        for b in self.branches:
            b.source.close()

    def poll(self, max_records: int):
        active = [b for b in self.branches if not b.ended]
        if not active:
            return [], True
        per = max(1, max_records // len(active))
        out: List[Any] = []
        # rotate the starting branch so no input starves under small batches
        n = len(self.branches)
        for i in range(n):
            b = self.branches[(self._rr + i) % n]
            if not b.ended:
                out.extend(b.poll(per))
        self._rr = (self._rr + 1) % n
        end = all(b.ended for b in self.branches)
        return out, end

    def snapshot_offsets(self):
        # per-branch (source offsets, watermark) — the watermark must rewind
        # with the offsets or replayed out-of-order elements would be judged
        # late against the crash-time watermark and lost
        return [
            (
                b.source.snapshot_offsets(),
                b.strategy._current if b.strategy else None,
            )
            for b in self.branches
        ]

    def restore_offsets(self, state):
        for b, (off, wm) in zip(self.branches, state):
            b.source.restore_offsets(off)
            b.ended = False
            if b.strategy is not None and wm is not None:
                b.strategy._current = wm

    def notify_checkpoint_complete(self, checkpoint_id: int, offsets=None):
        for b, entry in zip(self.branches, offsets or [(None, None)] * len(
            self.branches
        )):
            b.source.notify_checkpoint_complete(checkpoint_id, entry[0])


class IterationSource(Source):
    """Iteration head: upstream elements first, then feedback-queue drain
    (ref StreamIterationHead's feedback-queue poll loop). Ends only when the
    upstream is exhausted, the queue is empty, AND this poll returned no
    elements — so feedback generated while processing the final batch is
    never lost."""

    columnar = False

    def __init__(self, upstream, pre_ops, queue):
        self.upstream = upstream
        self.pre_ops = tuple(pre_ops)
        self.queue = queue
        self._up_done = False

    def open(self):
        self.upstream.open()

    def close(self):
        self.upstream.close()

    def poll(self, max_records: int):
        out: List[Any] = []
        if not self._up_done:
            polled, end = self.upstream.poll(max_records)
            self._up_done = end
            out.extend(_apply_ops(self.pre_ops, to_elements(polled)))
        while self.queue and len(out) < max(max_records, 1):
            out.append(self.queue.popleft())
        end = self._up_done and not self.queue and not out
        return out, end

    def snapshot_offsets(self):
        return (self.upstream.snapshot_offsets(), list(self.queue))

    def restore_offsets(self, state):
        up, pending = state
        self.upstream.restore_offsets(up)
        self.queue.clear()
        self.queue.extend(pending)
        self._up_done = False

    def notify_checkpoint_complete(self, checkpoint_id: int, offsets=None):
        self.upstream.notify_checkpoint_complete(
            checkpoint_id, offsets[0] if offsets is not None else None
        )
