"""Web monitor: JSON status endpoints over the MiniCluster.

The role of flink-runtime-web's WebRuntimeMonitor + handlers (SURVEY §2.9):
a small HTTP server exposing cluster overview, job list/detail, metric
snapshots, and the back-pressure signal (cycle-time percentiles standing in
for the reference's stack-trace sampling, see SURVEY §5: in the micro-batch
design back-pressure IS a growing cycle time).

Endpoints (reference REST shapes, docs/monitoring/rest_api.md):
    /overview                 cluster summary
    /jobs                     job ids + states
    /jobs/<jid>               job detail incl. JobMetrics
    /jobs/<jid>/metrics       full metric snapshot for the job
    /jobs/<jid>/backpressure  cycle-time percentiles
    /jobs/<jid>/traces        step-loop span traces as Chrome-trace JSON
                              (observability.tracing; docs/observability.md)
    /jobs/<jid>/recovery      per-attempt recovery phase breakdowns
                              (detect -> first-fire MTTR, warm vs full,
                              task-local cache hits/misses)
    /jobs/<jid>/elasticity    shard-loss degraded-mode state + rescale
                              history (runtime/elastic.py)
    /jobs/<jid>/keygroups     hot key-group top-k + occupancy/fill skew
                              (device-resident telemetry; ?k= bounds)
    /jobs/<jid>/pipeline      resident-pipeline health: per-shard ring
                              occupancy/duty-cycle/refusal series +
                              fire/consume latency percentiles
                              (observability.drain-stats, ISSUE 14)
    /jobs/<jid>/doctor        ranked pipeline-health findings with
                              evidence + config remedies, snapshot
                              embedded for offline replay
                              (observability.doctor, ISSUE 17)
    /jobs/<jid>/controller    self-tuning controller decision ledger:
                              knob moves/reverts/rebalances with
                              evidence, live actuator values
                              (controller.enabled, ISSUE 19)
    /metrics                  Prometheus text exposition over every job's
                              registry (text/plain, not JSON — scrape me)
    /jobs/<jid>/checkpoints   checkpoint history: id/duration/bytes/entries
                              + aborted attempts, the live failure-budget
                              state, and watchdog trips
                              (ref CheckpointStatsTracker + handlers/checkpoints/)
    /jobs/<jid>/plan          logical operator DAG (ref JobPlanHandler)
    /jobs/<jid>/vertices      plan nodes + job throughput (ref JobDetailsHandler)
    /jobs/<jid>/vertices/<vid>[/subtasks]  per-subtask rows
                              (ref JobVertexDetailsHandler)
    /jobs/<jid>/vertices/<vid>/metrics  per-vertex metric snapshot
                              (ref JobVertexMetricsHandler)
    /jobs/<jid>/vertices/<vid>/subtasktimes  per-subtask state timestamps
                              (ref SubtasksTimesHandler)
    /jobs/<jid>/vertices/<vid>/accumulators  per-vertex accumulators
                              (ref JobVertexAccumulatorsHandler)
    /jobs/<jid>/vertices/<vid>/subtasks/accumulators  all subtasks'
                              accumulators (ref SubtasksAllAccumulatorsHandler)
    /jobs/<jid>/vertices/<vid>/taskmanagers  subtasks grouped by TM
                              (ref JobVertexTaskManagersHandler)
    /jobs/<jid>/vertices/<vid>/checkpoints   vertex-scoped checkpoint
                              stats (ref JobVertexCheckpointsHandler)
    /jars/<id>/plan           dry-run plan of an uploaded program
                              (ref JarPlanHandler)
    /jobs/<jid>/vertices/<vid>/subtasks/<n>[/attempts/<a>]  one subtask's
                              current or historical attempt (ref
                              SubtaskCurrentAttemptDetailsHandler /
                              SubtaskExecutionAttemptDetailsHandler)
    /jobs/<jid>/checkpoints/config       (ref CheckpointConfigHandler)
    /jobs/<jid>/checkpoints/details/<id> one checkpoint's stats breakdown
                              (ref CheckpointStatsDetailsHandler)
    /jobs/<jid>/accumulators  user accumulators (ref JobAccumulatorsHandler)
    /jobs/<jid>/config        execution config (ref JobConfigHandler)
    /jobs/<jid>/exceptions    failure causes (ref JobExceptionsHandler)
    /joboverview[/running|/completed]  (ref CurrentJobsOverviewHandler)
    /taskmanagers[/<id>]      device-slot view (ref TaskManagersHandler)
    /config                   effective configuration (ref JobManagerConfigHandler)
    /web                      single-page HTML dashboard over these routes

HTTP job submission (ref JarUploadHandler / JarListHandler /
JarRunHandler / JarDeleteHandler — the Web UI's submission path; the
"jar" here is a Python module defining a builder function that returns a
ready-to-submit StreamExecutionEnvironment):
    POST   /jars/upload?name=<n>   body = module source -> {"id": ...}
    GET    /jars                   uploaded program list
    POST   /jars/<id>/run?entry=<fn>&job-name=<n>  -> {"jobid": ...}
    DELETE /jars/<id>
    POST   /jobs/<jid>/cancel | /jobs/<jid>/stop   (ref
           JobCancellationHandler / JobStoppingHandler)
    POST   /jobs/<jid>/savepoints?target-directory=D  live savepoint
           trigger (the CLI ACTION_SAVEPOINT role over HTTP)
    POST   /jobs/<jid>/cancel-with-savepoint?target-directory=D
           savepoint-then-cancel, one synchronous response (ref
           JobCancellationWithSavepointHandlers)
    DELETE /jobs/<jid>         cancel, REST-style
Like the reference, uploading a program means trusting it: the run
handler executes the module, and the plan handler also executes its
top-level code and builder to derive the DAG (a "dry run" only in that
nothing is submitted). The shared-secret auth (when configured)
gates these routes exactly like the read paths.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from flink_tpu.runtime.cluster import MiniCluster
from flink_tpu.runtime import security


class WebMonitor:
    """HTTP plane. When a shared secret is configured (see
    runtime/security.py — config keys or FLINK_TPU_AUTH_TOKEN), EVERY
    route requires it, queryable-state reads included: state values are
    exactly the data worth protecting (ref KvStateServerHandler).
    Clients send ``Authorization: Bearer <token>`` or ``?token=``."""

    def __init__(self, cluster: MiniCluster, host: str = "127.0.0.1",
                 port: int = 0, config=None, jar_dir: Optional[str] = None):
        self.cluster = cluster
        self._token = security.get_token(config)
        self._jar_dir = jar_dir    # created lazily on first upload
        self._jar_dir_owned = False
        self._jars = {}            # id -> {"name", "path", "uploaded"}
        self._next_jar = 1
        self._jar_lock = threading.Lock()
        monitor = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _authorized(self) -> bool:
                if monitor._token is None:
                    return True
                import hmac as _hmac
                auth = self.headers.get("Authorization", "")
                got = auth[7:] if auth.startswith("Bearer ") else None
                if got is None:
                    q = dict(urllib.parse.parse_qsl(
                        urllib.parse.urlsplit(self.path).query))
                    got = q.get("token")
                return isinstance(got, str) and _hmac.compare_digest(
                    got, monitor._token)

            def _deny(self):
                data = json.dumps({"error": "unauthorized"}).encode()
                self.send_response(401)
                self.send_header("Content-Type", "application/json")
                self.send_header("WWW-Authenticate", "Bearer")
                self.send_header("Content-Length", str(len(data)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if not self._authorized():
                    return self._deny()
                if urllib.parse.urlsplit(self.path).path == "/metrics":
                    # Prometheus scrape endpoint (text exposition, NOT
                    # JSON): every job's registry on the existing port
                    data = monitor._prometheus_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if urllib.parse.urlsplit(self.path).path in ("/web", "/web/"):
                    data = _DASHBOARD_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                try:
                    u = urllib.parse.urlsplit(self.path)
                    query = dict(urllib.parse.parse_qsl(u.query))
                    body = monitor._route(u.path, query)
                    code = 200 if body is not None else 404
                    body = body if body is not None else {"error": "not found"}
                except Exception as e:
                    code, body = 500, {"error": str(e)}
                self._json(code, body)

            def _json(self, code: int, body: dict):
                data = json.dumps(body, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            MAX_UPLOAD = 16 << 20      # program source size cap

            def _read_body(self):
                """(payload, error). Oversized bodies are NEVER buffered
                (413 without reading) — an unauthenticated or abusive
                client must not be able to exhaust server memory."""
                if "chunked" in self.headers.get(
                        "Transfer-Encoding", "").lower():
                    return None, (411, {"error": "length required"})
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                except ValueError:
                    return None, (400, {"error": "bad Content-Length"})
                if n > self.MAX_UPLOAD:
                    return None, (413, {"error": "body too large"})
                return (self.rfile.read(n) if n > 0 else b""), None

            def do_POST(self):
                if not self._authorized():
                    # drain a BOUNDED prefix so well-behaved clients see
                    # the 401 instead of a reset; huge bodies get cut off
                    # by Connection: close rather than buffered
                    try:
                        n = int(self.headers.get("Content-Length", 0)
                                or 0)
                    except ValueError:
                        n = 0
                    if 0 < n <= (64 << 10):
                        self.rfile.read(n)
                    return self._deny()
                payload, err = self._read_body()
                if err is not None:
                    return self._json(*err)
                u = urllib.parse.urlsplit(self.path)
                query = dict(urllib.parse.parse_qsl(u.query))
                try:
                    code, body = monitor._route_post(u.path, query,
                                                     payload)
                except Exception as e:
                    code, body = 500, {"error": str(e)}
                self._json(code, body)

            def do_DELETE(self):
                if not self._authorized():
                    return self._deny()
                u = urllib.parse.urlsplit(self.path)
                try:
                    code, body = monitor._route_delete(u.path)
                except Exception as e:
                    code, body = 500, {"error": str(e)}
                self._json(code, body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="web-monitor"
        )

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        if self._jar_dir_owned and self._jar_dir:
            import shutil

            shutil.rmtree(self._jar_dir, ignore_errors=True)
            self._jar_dir = None
            self._jar_dir_owned = False

    # -- helpers ---------------------------------------------------------
    def _prometheus_text(self) -> str:
        """Aggregate Prometheus exposition over every job's registry.
        Job attribution needs no extra labelling: each registry already
        scopes its metrics as jobs.<name>.<metric>, which the renderer
        turns into {job="<name>"} labels."""
        from flink_tpu.metrics.reporters import prometheus_text_from_items

        items = []
        seen = set()
        for rec in list(self.cluster.jobs.values()):
            reg = getattr(rec.env, "metric_registry", None)
            # concurrent submissions may share one env/registry; collect
            # each registry once or the scrape has duplicate series
            if reg is None or id(reg) in seen:
                continue
            seen.add(id(reg))
            items.extend(reg.items())
        return prometheus_text_from_items(items)

    @staticmethod
    def _plan_nodes(env) -> list:
        """The logical operator DAG of an environment as plan-JSON rows
        (shared by JobPlanHandler and JarPlanHandler analogs)."""
        from flink_tpu.graph.stream_graph import parents_of, walk_dag

        return [
            {
                "id": t.id,
                "type": type(t).__name__.replace("Transformation", ""),
                "description": getattr(t, "kind", None) or t.name,
                "inputs": [p.id for p in parents_of(t)],
            }
            for t in walk_dag(getattr(env, "_sinks", []))
        ]

    def _job_vertex(self, jid: str, vid: int):
        rec = self.cluster.jobs.get(jid)
        eg = getattr(rec, "execution_graph", None) if rec else None
        if eg is None:
            return None
        return eg.job_vertices.get(vid)

    @staticmethod
    def _subtask_row(v) -> dict:
        cur = v.current
        return {
            "subtask": v.subtask_index,
            "status": cur.state,
            "attempt": cur.attempt,
            "host": "tm-local",
            "start-time": int(
                cur.state_times.get("CREATED", 0) * 1000),
            "end-time": int(max(
                (t for s, t in cur.state_times.items()
                 if s in ("FINISHED", "FAILED", "CANCELED")),
                default=0,
            ) * 1000) or -1,
        }

    @staticmethod
    def _checkpoint_stats(rec) -> list:
        live = getattr(rec.env, "_live_metrics", None)
        stats = (getattr(live, "checkpoint_stats", None) or [])
        if not stats and rec.handle is not None:
            stats = rec.handle.metrics.checkpoint_stats or []
        return stats

    @staticmethod
    def _attempt_row(v, a) -> dict:
        return {
            "subtask": v.subtask_index,
            "attempt": a.attempt,
            "status": a.state,
            "host": "tm-local",
            "state-times": {k: int(t * 1000)
                            for k, t in a.state_times.items()},
            "failure-cause": a.failure_cause,
        }

    # -- job submission (ref JarUploadHandler / JarRunHandler) -----------
    def _route_post(self, path, query, payload):
        import os
        import tempfile
        import time as _time

        if path == "/jars/upload":
            if not payload:
                return 400, {"error": "empty program body"}
            with self._jar_lock:
                if self._jar_dir is None:
                    self._jar_dir = tempfile.mkdtemp(
                        prefix="flink-tpu-jars-")
                    self._jar_dir_owned = True
                os.makedirs(self._jar_dir, exist_ok=True)
                jid = f"prog-{self._next_jar}"
                self._next_jar += 1
                name = query.get("name", f"{jid}.py")
                dest = os.path.join(self._jar_dir, f"{jid}.py")
                with open(dest, "wb") as f:
                    f.write(payload)
                self._jars[jid] = {
                    "id": jid, "name": name, "path": dest,
                    "uploaded": int(_time.time() * 1000),
                }
            return 200, {"id": jid, "status": "success"}
        m = re.fullmatch(r"/jobs/([^/]+)/savepoints", path)
        if m:
            # savepoint trigger over HTTP (the CLI's ACTION_SAVEPOINT
            # role; the reference added the REST form in later versions)
            sp, err = self._trigger_savepoint(m.group(1), query)
            if err is not None:
                return err
            return 200, {"status": "completed", "savepoint-path": sp}
        m = re.fullmatch(r"/jobs/([^/]+)/(cancel|stop)", path)
        if m:
            # ref JobCancellationHandler / JobStoppingHandler
            try:
                if m.group(2) == "cancel":
                    self.cluster.cancel(m.group(1))
                else:
                    self.cluster.stop(m.group(1))
            except KeyError:
                return 404, {"error": f"no job {m.group(1)!r}"}
            return 202, {"status": f"{m.group(2)}-requested"}
        m = re.fullmatch(r"/jobs/([^/]+)/cancel-with-savepoint", path)
        if m:
            # ref JobCancellationWithSavepointHandlers: savepoint, then
            # cancel only once the savepoint completed (never lose the
            # state cut). The reference splits this into trigger +
            # in-progress polling handlers; the step-boundary savepoint
            # here completes synchronously, so one response carries the
            # path (the polling handler's terminal payload).
            sp, err = self._trigger_savepoint(m.group(1), query)
            if err is not None:
                return err
            self.cluster.cancel(m.group(1))
            return 200, {"status": "success", "savepoint-path": sp,
                         "cancellation": "requested"}
        m = re.fullmatch(r"/jars/([^/]+)/run", path)
        if m:
            with self._jar_lock:
                jar = self._jars.get(m.group(1))
            if jar is None:
                return 404, {"error": f"no program {m.group(1)!r}"}
            from flink_tpu.runtime.worker import load_builder

            entry = query.get("entry", "build")
            try:
                builder = load_builder(f"{jar['path']}:{entry}")
            except (FileNotFoundError, OSError):
                # raced with DELETE /jars/<id>: the program is gone
                return 404, {"error": f"no program {m.group(1)!r}"}
            env = builder()
            jobid = self.cluster.submit(
                env, query.get("job-name", jar["name"])
            )
            return 200, {"jobid": jobid}
        return 404, {"error": "not found"}

    def _trigger_savepoint(self, jid: str, query: dict):
        """-> (savepoint_path, None) or (None, (code, body)) — the one
        trigger/error mapping shared by /savepoints and
        /cancel-with-savepoint."""
        target = query.get("target-directory")
        if not target:
            return None, (400, {"error": "missing ?target-directory="})
        try:
            return self.cluster.trigger_savepoint(jid, target), None
        except KeyError:
            return None, (404, {"error": f"no job {jid!r}"})
        except NotImplementedError as e:
            return None, (501, {"error": str(e)})  # stage can't savepoint
        except RuntimeError as e:
            return None, (409, {"error": str(e)})

    def _route_delete(self, path):
        import os

        m = re.fullmatch(r"/jars/([^/]+)", path)
        if m:
            with self._jar_lock:
                jar = self._jars.pop(m.group(1), None)
            if jar is None:
                return 404, {"error": f"no program {m.group(1)!r}"}
            try:
                os.unlink(jar["path"])
            except OSError:
                pass
            return 200, {"status": "success"}
        m = re.fullmatch(r"/jobs/([^/]+)", path)
        if m:
            # ref JobCancellationHandler (DELETE /jobs/:jobid and the
            # legacy GET /jobs/:jobid/cancel both cancel)
            try:
                self.cluster.cancel(m.group(1))
            except KeyError:
                return 404, {"error": f"no job {m.group(1)!r}"}
            return 202, {"status": "cancellation-requested"}
        return 404, {"error": "not found"}

    # -- routing ---------------------------------------------------------
    def _route(self, path: str, query: Optional[dict] = None) -> Optional[dict]:
        query = query or {}
        if path in ("/", "/overview"):
            jobs = self.cluster.list_jobs()
            return {
                "jobs-running": sum(j["state"] == "RUNNING" for j in jobs),
                "jobs-finished": sum(j["state"] == "FINISHED" for j in jobs),
                "jobs-cancelled": sum(j["state"] == "CANCELED" for j in jobs),
                "jobs-failed": sum(j["state"] == "FAILED" for j in jobs),
                "flink-tpu-version": "0.1",
            }
        if path == "/jobs":
            return {"jobs": self.cluster.list_jobs()}
        if path == "/jars":
            # ref JarListHandler (upload order; server paths stay private)
            with self._jar_lock:
                files = [
                    {"id": j["id"], "name": j["name"],
                     "uploaded": j["uploaded"]}
                    for j in sorted(self._jars.values(),
                                    key=lambda j: j["uploaded"])
                ]
            return {"files": files}
        if path in ("/joboverview", "/joboverview/running",
                    "/joboverview/completed"):
            # ref CurrentJobsOverviewHandler + its running/completed splits
            jobs = self.cluster.list_jobs()
            running = [j for j in jobs if j["state"] == "RUNNING"]
            done = [j for j in jobs if j["state"] != "RUNNING"]
            if path.endswith("/running"):
                return {"jobs": running}
            if path.endswith("/completed"):
                return {"jobs": done}
            return {"running": running, "finished": done}
        if path == "/taskmanagers":
            # ref TaskManagersHandler: the in-process MiniCluster is one
            # logical TM whose "slots" are the accelerator devices
            import jax

            devs = jax.devices()
            return {"taskmanagers": [{
                "id": "tm-local",
                "path": "inprocess://minicluster",
                "slotsNumber": len(devs),
                # clamped: concurrent jobs can exceed devices (each runs
                # SPMD over all of them), and the reference shape
                # guarantees 0..slotsNumber
                "freeSlots": max(0, len(devs) - sum(
                    j["state"] == "RUNNING"
                    for j in self.cluster.list_jobs()
                )),
                "hardware": {
                    "devices": [str(d) for d in devs],
                    "platform": devs[0].platform if devs else "none",
                },
            }]}
        m = re.fullmatch(r"/taskmanagers/([^/]+)", path)
        if m:
            tms = self._route("/taskmanagers")["taskmanagers"]
            for tm in tms:
                if tm["id"] == m.group(1):
                    return tm
            return None
        m = re.fullmatch(r"/jobs/([^/]+)", path)
        if m:
            try:
                return self.cluster.job_detail(m.group(1))
            except KeyError:
                return None
        m = re.fullmatch(r"/jobs/([^/]+)/metrics", path)
        if m:
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            return rec.env.metric_registry.snapshot()
        m = re.fullmatch(r"/jobs/([^/]+)/state/([^/]+)", path)
        if m:
            from flink_tpu.runtime.queryable import parse_key

            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            if "key" not in query:
                return {"ok": False, "error": "missing ?key="}
            try:
                value = rec.env._kv_registry.query(
                    m.group(2), parse_key(query["key"])
                )
            except KeyError as e:
                return {"ok": False, "error": str(e)}
            return {"ok": True, "value": value}
        m = re.fullmatch(r"/jobs/([^/]+)/plan", path)
        if m:
            # ref JobPlanHandler: the logical operator DAG as JSON
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            return {"jid": m.group(1),
                    "plan": {"nodes": self._plan_nodes(rec.env)}}
        m = re.fullmatch(r"/jars/([^/]+)/plan", path)
        if m:
            # ref JarPlanHandler: build the program's plan WITHOUT
            # submitting it — the dry-run the reference offers before
            # JarRunHandler
            with self._jar_lock:
                jar = self._jars.get(m.group(1))
            if jar is None:
                return None
            from flink_tpu.runtime.worker import load_builder

            entry = query.get("entry", "build")
            try:
                builder = load_builder(f"{jar['path']}:{entry}")
            except (FileNotFoundError, OSError):
                return None            # raced with DELETE /jars/<id>
            # builder errors surface as 500 with the real message (the
            # /run handler's idiom) — a program bug is not a 404
            return {"id": m.group(1),
                    "plan": {"nodes": self._plan_nodes(builder())}}
        m = re.fullmatch(r"/jobs/([^/]+)/vertices", path)
        if m:
            # ref JobDetailsHandler's vertices array: served from the
            # ExecutionGraph (per-vertex state + attempt counters) with
            # job-level throughput attached (the micro-batch design runs
            # one fused step, so per-vertex counters collapse to the
            # job's — served explicitly rather than faked per vertex)
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            detail = self.cluster.job_detail(m.group(1))
            eg = getattr(rec, "execution_graph", None)
            if eg is not None:
                return {
                    "jid": m.group(1),
                    "state": eg.state,
                    "restarts": eg.restarts,
                    "vertices": eg.vertices_summary(),
                    "job-metrics": detail.get("metrics", {}),
                }
            plan = self._route(f"/jobs/{m.group(1)}/plan")
            return {
                "jid": m.group(1),
                "vertices": plan["plan"]["nodes"],
                "job-metrics": detail.get("metrics", {}),
            }
        m = re.fullmatch(r"/jobs/([^/]+)/vertices/(\d+)/metrics", path)
        if m:
            # ref JobVertexMetricsHandler: the micro-batch design runs
            # one fused step, so per-vertex counters ARE the job's —
            # served per vertex for handler parity, attribution explicit
            jv = self._job_vertex(m.group(1), int(m.group(2)))
            if jv is None:
                return None
            # _job_vertex non-None proves the record exists
            rec = self.cluster.jobs[m.group(1)]
            return {
                "id": int(m.group(2)),
                "name": jv.name,
                "attribution": "job-level (fused micro-batch step)",
                "metrics": rec.env.metric_registry.snapshot(),
            }
        m = re.fullmatch(r"/jobs/([^/]+)/vertices/(\d+)"
                         r"(/subtasks)?", path)
        if m:
            # ref JobVertexDetailsHandler: per-subtask rows for one
            # logical operator (subtask index, state, attempt, timings)
            jv = self._job_vertex(m.group(1), int(m.group(2)))
            if jv is None:
                return None
            return {
                "jid": m.group(1),
                "id": int(m.group(2)),
                "name": jv.name,
                "parallelism": jv.parallelism,
                "subtasks": [
                    self._subtask_row(v) for v in jv.vertices
                ],
            }
        m = re.fullmatch(r"/jobs/([^/]+)/vertices/(\d+)/accumulators",
                         path)
        if m:
            # ref JobVertexAccumulatorsHandler: the fused micro-batch
            # step accumulates at job scope, served per vertex for
            # handler parity with the attribution explicit (the same
            # honesty as /vertices/<v>/metrics)
            jv = self._job_vertex(m.group(1), int(m.group(2)))
            if jv is None:
                return None
            job_accs = self._route(f"/jobs/{m.group(1)}/accumulators")
            return {
                "id": int(m.group(2)),
                "attribution": "job-level (fused micro-batch step)",
                "user-accumulators":
                    job_accs["user-task-accumulators"],
            }
        m = re.fullmatch(
            r"/jobs/([^/]+)/vertices/(\d+)/subtasks/accumulators", path)
        if m:
            # ref SubtasksAllAccumulatorsHandler
            jv = self._job_vertex(m.group(1), int(m.group(2)))
            if jv is None:
                return None
            job_accs = self._route(f"/jobs/{m.group(1)}/accumulators")
            return {
                "id": int(m.group(2)),
                "parallelism": jv.parallelism,
                "subtasks": [{
                    "subtask": v.subtask_index,
                    "attempt": v.current.attempt,
                    "host": "tm-local",
                    "user-accumulators":
                        job_accs["user-task-accumulators"],
                } for v in jv.vertices],
            }
        m = re.fullmatch(r"/jobs/([^/]+)/vertices/(\d+)/taskmanagers",
                         path)
        if m:
            # ref JobVertexTaskManagersHandler: subtask rows aggregated
            # by host TaskManager (the MiniCluster is one logical TM)
            jv = self._job_vertex(m.group(1), int(m.group(2)))
            if jv is None:
                return None
            counts: dict = {}
            for v in jv.vertices:
                counts[v.current.state] = counts.get(
                    v.current.state, 0) + 1
            return {
                "id": int(m.group(2)),
                "name": jv.name,
                "taskmanagers": [{
                    "host": "tm-local",
                    "status-counts": counts,
                    "subtasks": len(jv.vertices),
                }],
            }
        m = re.fullmatch(r"/jobs/([^/]+)/vertices/(\d+)/checkpoints",
                         path)
        if m:
            # ref JobVertexCheckpointsHandler: checkpoint stats scoped
            # to one vertex. One fused stage snapshots at the step
            # boundary, so the job rows are the vertex rows with the
            # attribution explicit.
            jv = self._job_vertex(m.group(1), int(m.group(2)))
            if jv is None:
                return None
            rec = self.cluster.jobs[m.group(1)]
            return {
                "id": int(m.group(2)),
                "name": jv.name,
                "attribution": "job-level (fused stage snapshot)",
                "checkpoints": self._checkpoint_stats(rec),
            }
        m = re.fullmatch(r"/jobs/([^/]+)/vertices/(\d+)/subtasktimes",
                         path)
        if m:
            # ref SubtasksTimesHandler: per-subtask state-transition
            # timestamps
            jv = self._job_vertex(m.group(1), int(m.group(2)))
            if jv is None:
                return None
            return {
                "id": int(m.group(2)),
                "name": jv.name,
                "subtasks": [{
                    "subtask": v.subtask_index,
                    "timestamps": {
                        k: int(t * 1000)
                        for k, t in v.current.state_times.items()
                    },
                } for v in jv.vertices],
            }
        m = re.fullmatch(
            r"/jobs/([^/]+)/vertices/(\d+)/subtasks/(\d+)"
            r"(?:/attempts/(\d+))?", path,
        )
        if m:
            # ref SubtaskCurrentAttemptDetailsHandler (+ the
            # /attempts/<n> form, SubtaskExecutionAttemptDetailsHandler:
            # the FULL attempt history is addressable, not just the
            # current execution)
            jv = self._job_vertex(m.group(1), int(m.group(2)))
            if jv is None:
                return None
            idx = int(m.group(3))
            if idx >= len(jv.vertices):
                return None
            v = jv.vertices[idx]
            if m.group(4) is not None:
                a_no = int(m.group(4))
                for a in v.attempts:
                    if a.attempt == a_no:
                        return self._attempt_row(v, a)
                return None
            return {
                **self._attempt_row(v, v.current),
                "prior-attempts": [
                    self._attempt_row(v, a) for a in v.attempts[:-1]
                ],
            }
        m = re.fullmatch(r"/jobs/([^/]+)/checkpoints/config", path)
        if m:
            # ref CheckpointConfigHandler
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            env = rec.env
            cfg = getattr(env, "config", None)
            snap_mode = (
                cfg.get_str("checkpoint.mode", "full")
                if cfg is not None else "full"
            )
            return {
                "mode": "exactly_once",
                "interval-steps": getattr(
                    env, "checkpoint_interval_steps", 0) or 0,
                "directory": getattr(env, "checkpoint_dir", None),
                "retained": getattr(
                    cfg, "get_int", lambda *a: 2)("checkpoint.retain", 2),
                "snapshot-mode": snap_mode,
                "async": (
                    cfg.get_bool("checkpoint.async",
                                 snap_mode == "incremental")
                    if cfg is not None else False
                ),
                "compact-every": getattr(
                    cfg, "get_int", lambda *a: 8
                )("checkpoint.compact-every", 8),
                # failure containment (docs/fault-tolerance.md)
                "tolerable-failures": getattr(
                    cfg, "get_int", lambda *a: 0
                )("checkpoint.tolerable-failures", 0),
                "timeout-s": getattr(
                    cfg, "get_float", lambda *a: 600.0
                )("checkpoint.timeout", 600.0),
                "min-pause-s": getattr(
                    cfg, "get_float", lambda *a: 0.0
                )("checkpoint.min-pause", 0.0),
                "watchdog": (
                    cfg.get_bool("watchdog.enabled", True)
                    if cfg is not None else True
                ),
                "externalization": {"enabled": True,
                                    "delete_on_cancellation": False},
            }
        m = re.fullmatch(r"/jobs/([^/]+)/checkpoints/details/(\d+)", path)
        if m:
            # ref CheckpointStatsDetailsHandler: one checkpoint's stats
            # with the per-vertex breakdown. The micro-batch design
            # snapshots ONE fused stage at the step boundary, so the
            # job-level numbers are attributed to the fused stage row
            # explicitly (same honesty as /vertices) with the operator
            # rows listed for addressability.
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            cid = int(m.group(2))
            stats = self._checkpoint_stats(rec)
            row = next((s for s in stats if s["id"] == cid), None)
            if row is None:
                return None
            eg = getattr(rec, "execution_graph", None)
            tasks = {}
            if eg is not None:
                for vid, jv in eg.job_vertices.items():
                    tasks[str(vid)] = {
                        "name": jv.name,
                        "parallelism": jv.parallelism,
                        "acknowledged": jv.parallelism,
                    }
            out = {
                "id": cid,
                "status": row.get("status", "completed").upper(),
                "trigger-timestamp-ms": row["trigger_ms"],
                "duration-ms": row["duration_ms"],
                "state-size-bytes": row["bytes"],
                "entries": row["entries"],
                "fused-stage": {
                    "duration-ms": row["duration_ms"],
                    "state-size-bytes": row["bytes"],
                },
                "tasks": tasks,
            }
            if row.get("failure_reason"):
                out["failure-cause"] = row["failure_reason"]
            return out
        m = re.fullmatch(r"/jobs/([^/]+)/accumulators", path)
        if m:
            # ref JobAccumulatorsHandler
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            accs = {}
            if rec.handle is not None and rec.handle.accumulator_results:
                accs = rec.handle.accumulator_results
            return {"job-accumulators": [], "user-task-accumulators": [
                {"name": k, "value": str(v)} for k, v in sorted(accs.items())
            ]}
        m = re.fullmatch(r"/jobs/([^/]+)/config", path)
        if m:
            # ref JobConfigHandler: per-job execution configuration
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            env = rec.env
            return {
                "jid": m.group(1),
                "name": rec.name,
                "execution-config": {
                    "execution-mode": "PIPELINED",
                    "job-parallelism": getattr(env, "parallelism", 1),
                    "max-parallelism": getattr(env, "max_parallelism", 128),
                    "batch-size": getattr(env, "batch_size", None),
                    "object-reuse-mode": False,
                    "user-config": {
                        k: str(v) for k, v in sorted(getattr(
                            getattr(env, "config", None), "_data", {}
                        ).items())
                    },
                },
            }
        m = re.fullmatch(r"/jobs/([^/]+)/exceptions", path)
        if m:
            # ref JobExceptionsHandler
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            return {
                "root-exception": rec.error,
                "truncated": False,
                "all-exceptions": [rec.error] if rec.error else [],
            }
        if path in ("/config", "/jobmanager/config"):
            # ref JobManagerConfigHandler serves cluster-level config; the
            # MiniCluster has no separate cluster Configuration, so the
            # MERGED view over every job's config is served (later
            # submissions win on key clashes). Snapshot under no lock
            # hazard: list() copies before iterating (submit() mutates
            # the dict from other threads).
            merged = {}
            for rec in list(self.cluster.jobs.values()):
                data = getattr(getattr(rec.env, "config", None), "_data",
                               None)
                if data:
                    merged.update(data)
            return [
                {"key": k, "value": str(v)}
                for k, v in sorted(merged.items())
            ]
        m = re.fullmatch(r"/jobs/([^/]+)/checkpoints", path)
        if m:
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            stats = self._checkpoint_stats(rec)
            # aborted attempts ride the same history (failure-budget
            # containment) but must not skew the completion summaries
            done = [
                s for s in stats if s.get("status", "completed") != "aborted"
            ]
            aborted = [s for s in stats if s.get("status") == "aborted"]
            durs = [s["duration_ms"] for s in done]
            sizes = [s["bytes"] for s in done]

            def _mm(vals):
                return {
                    "min": min(vals) if vals else 0,
                    "max": max(vals) if vals else 0,
                    "avg": sum(vals) / len(vals) if vals else 0,
                }

            # async/incremental split (flink_tpu/checkpointing): sync-ms
            # is the step-loop stall, async-ms the background
            # materialization; bytes split by full base vs delta
            full = [s for s in done if s.get("kind", "full") == "full"]
            delta = [s for s in done if s.get("kind") == "delta"]
            live = getattr(rec.env, "_live_metrics", None)
            src = live or (rec.handle.metrics if rec.handle else None)
            budget = getattr(src, "failure_budget", None)
            return {
                "counts": {
                    "completed": len(done),
                    "aborted": len(aborted),
                    "declined": getattr(src, "checkpoints_declined", 0),
                    "full": len(full),
                    "incremental": len(delta),
                },
                # live failure-budget state (checkpointing/policy.py)
                "failure-budget": (
                    budget.state() if budget is not None else None
                ),
                "watchdog-trips": getattr(src, "watchdog_trips", 0),
                "summary": {
                    "duration-ms": _mm(durs),
                    "state-size-bytes": _mm(sizes),
                    "sync-ms": _mm([
                        s.get("sync_ms", s["duration_ms"]) for s in done
                    ]),
                    "async-ms": _mm([
                        s.get("async_ms", 0.0) for s in done
                    ]),
                    "bytes-full": sum(s["bytes"] for s in full),
                    "bytes-incremental": sum(s["bytes"] for s in delta),
                    "staging-wait-ms": _mm([
                        s.get("staging_wait_ms", 0.0) for s in done
                    ]),
                },
                "history": stats[-50:],
            }
        m = re.fullmatch(r"/jobs/([^/]+)/traces", path)
        if m:
            # step-loop span traces as Chrome-trace JSON (metrics/tracing
            # SpanTracer; load in chrome://tracing / ui.perfetto.dev).
            # Served live while the job runs AND after it finishes (the
            # tracer stays attached to the environment).
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None       # JSON 404: unknown job id
            tracer = getattr(rec.env, "_span_tracer", None)
            if tracer is None:
                return {
                    "enabled": False,
                    "traceEvents": [],
                    "hint": "set observability.tracing: true in the job "
                            "configuration to record step-loop spans",
                }
            return {"enabled": True, **tracer.to_chrome_trace()}
        m = re.fullmatch(r"/jobs/([^/]+)/keygroups", path)
        if m:
            # hot-key-group top-k: occupancy (who holds state) + sampled
            # fill counts (who receives traffic) from the device-resident
            # skew telemetry; ?k= bounds the list (default 10)
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            report_fn = getattr(rec.env, "_kg_report", None)
            if report_fn is None:
                return {
                    "available": False,
                    "hint": "key-group telemetry is recorded by windowed "
                            "keyed stages; this job has none (yet)",
                }
            try:
                k = max(1, min(int(query.get("k", 10)), 1000))
            except ValueError:
                k = 10
            return {"available": True, **report_fn(k)}
        m = re.fullmatch(r"/jobs/([^/]+)/recovery", path)
        if m:
            # per-attempt recovery phase breakdowns (metrics/recovery.py):
            # detect/settle/backoff/restore_plan/fetch/stage/compile ->
            # first-fire, plus warm-vs-full counts and the task-local
            # cache hit/miss ledger — the MTTR story of this job
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None       # JSON 404: unknown job id
            report_fn = getattr(rec.env, "_recovery_report", None)
            if report_fn is None:
                return {
                    "available": False,
                    "hint": "recovery instrumentation is recorded by "
                            "windowed keyed stages; this job has none "
                            "(yet)",
                }
            return {"available": True, **report_fn()}
        m = re.fullmatch(r"/jobs/([^/]+)/pipeline", path)
        if m:
            # resident-pipeline health (ISSUE 14): the drain flight
            # recorder's consolidated view — per-shard ring occupancy /
            # duty-cycle / publish-refusal series, drain-interior counter
            # totals, event-to-fire and publish-to-consume percentiles,
            # and the resident-aware attribution verdict
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None       # JSON 404: unknown job id
            report_fn = getattr(rec.env, "_pipeline_report", None)
            if report_fn is None:
                return {
                    "available": False,
                    "hint": "pipeline telemetry is recorded by resident-"
                            "loop windowed stages with observability."
                            "drain-stats on; this job has none (yet)",
                }
            return report_fn()
        m = re.fullmatch(r"/jobs/([^/]+)/doctor", path)
        if m:
            # the pipeline doctor (ISSUE 17): every telemetry plane
            # joined into one snapshot and run through the ranked-
            # findings rule engine (metrics/doctor.py) — each finding
            # carries evidence values and a concrete config remedy; the
            # snapshot is embedded so `python -m flink_tpu.doctor` can
            # replay the diagnosis offline
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None       # JSON 404: unknown job id
            report_fn = getattr(rec.env, "_doctor_report", None)
            if report_fn is None:
                return {
                    "available": False,
                    "hint": "the doctor runs over windowed keyed "
                            "stages' telemetry; this job has none (yet)",
                }
            return report_fn()
        m = re.fullmatch(r"/jobs/([^/]+)/controller", path)
        if m:
            # the self-tuning runtime controller (ISSUE 19): decision
            # ledger (tune/revert/rebalance entries with before/after
            # evidence), live actuator values, probation/cooldown state
            # (runtime/controller.py; controller.enabled gates it)
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None       # JSON 404: unknown job id
            report_fn = getattr(rec.env, "_controller_report", None)
            if report_fn is None:
                return {
                    "available": False,
                    "hint": "the controller services windowed keyed "
                            "stages; this job has none (yet)",
                }
            return report_fn()
        m = re.fullmatch(r"/jobs/([^/]+)/elasticity", path)
        if m:
            # elastic degraded-mode state (runtime/elastic.py): full vs
            # current shard count, lost devices, and the rescale history
            # (degrade + scale-back rows with per-transition MTTR) — the
            # shard-loss survival story of this job
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None       # JSON 404: unknown job id
            report_fn = getattr(rec.env, "_elasticity_report", None)
            if report_fn is None:
                return {
                    "available": False,
                    "hint": "elasticity state is recorded by windowed "
                            "keyed stages; this job has none (yet)",
                }
            return {"available": True, **report_fn()}
        m = re.fullmatch(r"/jobs/([^/]+)/backpressure", path)
        if m:
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            snap = rec.env.metric_registry.snapshot(
                f"jobs.{rec.name}.cycle_time_ms"
            )
            hist = next(iter(snap.values()), {"count": 0})
            count = hist.get("count", 0)
            p99 = hist.get("p99", 0)
            p50 = hist.get("p50", 0) or 1e-9
            # ratio in the spirit of the reference's OK/LOW/HIGH
            # thresholds (BackPressureStatsTracker)
            ratio = min(1.0, (p99 / p50 - 1.0) / 10.0) if count else 0.0
            level = ("ok" if ratio <= 0.10
                     else "low" if ratio <= 0.5 else "high")
            out = {
                "status": "ok",
                "backpressure-level": level,
                "ratio": ratio,
                "cycle-time-ms": hist,
            }
            # cause attribution: measured per-cycle phase decomposition
            # (source-starved / host-bound / device-bound / sink-bound)
            # replacing the reference's stack-trace sampling
            report_fn = getattr(rec.env, "_backpressure_report", None)
            if report_fn is not None:
                out["attribution"] = report_fn()
            # per-phase histograms + end-to-end latency markers
            phases = rec.env.metric_registry.snapshot(
                f"jobs.{rec.name}.phase_"
            )
            if phases:
                out["phase-histograms-ms"] = phases
            lat = rec.env.metric_registry.snapshot(
                f"jobs.{rec.name}.record_latency_ms"
            )
            if lat:
                out["record-latency-ms"] = next(iter(lat.values()))
            return out
        return None


# Single-page dashboard over the JSON routes (the role of the reference's
# AngularJS web-dashboard, flink-runtime-web/web-dashboard — rebuilt as one
# dependency-free page: job list -> per-job metrics, back-pressure
# attribution, and checkpoint history, auto-refreshing).
_DASHBOARD_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>flink-tpu dashboard</title>
<style>
 body{font:13px/1.5 system-ui,sans-serif;margin:0;background:#f4f5f7;color:#172b4d}
 header{background:#172b4d;color:#fff;padding:10px 18px;font-size:16px}
 header span{opacity:.65;font-size:12px;margin-left:10px}
 main{padding:14px 18px;max-width:1100px}
 table{border-collapse:collapse;width:100%;background:#fff;margin:8px 0 18px}
 th,td{padding:6px 10px;border:1px solid #dfe1e6;text-align:left;font-size:12px}
 th{background:#fafbfc}
 .state{font-weight:600}
 .RUNNING{color:#0747a6}.FINISHED{color:#006644}.FAILED{color:#bf2600}
 .CANCELED{color:#6b778c}
 h2{font-size:14px;margin:16px 0 4px}
 .pill{display:inline-block;padding:1px 8px;border-radius:9px;background:#dfe1e6;
       font-size:11px;margin-left:6px}
 .ok{background:#abf5d1}.low{background:#fff0b3}.high{background:#ffbdad}
 tr.sel{outline:2px solid #4c9aff}
 #err{color:#bf2600}
</style></head><body>
<header>flink-tpu<span>web dashboard — click a job for details</span></header>
<main>
 <div id="err"></div>
 <h2>Overview <span id="ov" class="pill"></span></h2>
 <h2>Jobs</h2><table id="jobs"><tr><th>id</th><th>name</th><th>state</th>
  <th>duration</th></tr></table>
 <div id="detail" style="display:none">
  <h2>Vertices <span id="jstate" class="pill"></span></h2>
  <table id="vx"><tr><th>operator</th><th>type</th><th>status</th>
   <th>attempt</th></tr></table>
  <div id="subwrap" style="display:none">
   <h2>Subtasks — <span id="subname"></span></h2>
   <table id="subt"><tr><th>subtask</th><th>status</th><th>attempt</th>
    <th>host</th></tr></table>
  </div>
  <h2>Metrics — <span id="jname"></span></h2><table id="mx"></table>
  <h2>Back-pressure <span id="bp" class="pill"></span></h2><table id="bpt"></table>
  <h2>Checkpoints <span id="ckn" class="pill"></span></h2>
  <table id="ck"><tr><th>id</th><th>duration ms</th><th>bytes</th>
   <th>entries</th></tr></table>
 </div>
</main><script>
let sel=null;
const TOK=new URLSearchParams(location.search).get("token");
const J=async p=>{if(TOK)p+=(p.includes("?")?"&":"?")+"token="+encodeURIComponent(TOK);const r=await fetch(p);if(!r.ok)throw new Error(p+" -> "+r.status);
 return r.json()};
const fmtDur=ms=>ms<0?"-":(ms/1000).toFixed(1)+"s";
async function showSubtasks(jid,vid,name){
 try{
  const d=await J("/jobs/"+jid+"/vertices/"+vid);
  document.getElementById("subwrap").style.display="";
  document.getElementById("subname").textContent=name;
  const t=document.getElementById("subt");
  while(t.rows.length>1)t.deleteRow(1);
  for(const s of d.subtasks||[]){
   const r=t.insertRow();
   r.insertCell().textContent=s.subtask;
   const c=r.insertCell();c.textContent=s.status;
   c.className="state "+(s.status||"");
   r.insertCell().textContent=s.attempt;
   r.insertCell().textContent=s.host;
  }
 }catch(e){document.getElementById("err").textContent=""+e}
}
async function tick(){
 try{
  document.getElementById("err").textContent="";
  const ov=await J("/overview");
  document.getElementById("ov").textContent=
   `running ${ov["jobs-running"]} / finished ${ov["jobs-finished"]} / failed ${ov["jobs-failed"]}`;
  const jobs=(await J("/jobs")).jobs;
  const t=document.getElementById("jobs");
  while(t.rows.length>1)t.deleteRow(1);
  for(const j of jobs){
   const r=t.insertRow();r.style.cursor="pointer";
   if(j.jid===sel)r.className="sel";
   r.onclick=()=>{sel=j.jid;
    document.getElementById("subwrap").style.display="none";tick()};
   r.insertCell().textContent=j.jid;
   r.insertCell().textContent=j.name;
   const c=r.insertCell();c.textContent=j.state;c.className="state "+j.state;
   r.insertCell().textContent=fmtDur(j.duration);
  }
  if(!sel&&jobs.length)sel=jobs[jobs.length-1].jid;
  if(!sel)return;
  const d=await J("/jobs/"+sel);
  document.getElementById("detail").style.display="";
  document.getElementById("jname").textContent=d.name;
  const mx=document.getElementById("mx");mx.innerHTML="";
  for(const[k,v]of Object.entries(d.metrics||{})){
   const r=mx.insertRow();r.insertCell().textContent=k;
   r.insertCell().textContent=v;
  }
  const vx=await J("/jobs/"+sel+"/vertices");
  const js=document.getElementById("jstate");
  js.textContent=(vx.state||"")+(vx.restarts?` / ${vx.restarts} restarts`:"");
  const vt=document.getElementById("vx");
  while(vt.rows.length>1)vt.deleteRow(1);
  for(const v of vx.vertices||[]){
   const r=vt.insertRow();
   r.style.cursor="pointer";
   r.onclick=()=>showSubtasks(sel,v.id,v.name||v.description||"");
   r.insertCell().textContent=v.name||v.description||"";
   r.insertCell().textContent=v.type;
   const c=r.insertCell();c.textContent=v.status||"";
   c.className="state "+(v.status||"");
   r.insertCell().textContent=v.attempt||"";
  }
  const bp=await J("/jobs/"+sel+"/backpressure");
  const lv=bp["backpressure-level"]||"ok";
  const pb=document.getElementById("bp");
  pb.textContent=(bp.attribution&&bp.attribution.classification)||lv;
  pb.className="pill "+lv;
  const bt=document.getElementById("bpt");bt.innerHTML="";
  for(const[k,v]of Object.entries((bp.attribution||{})["phase-ewma-ms"]||{})){
   const r=bt.insertRow();r.insertCell().textContent=k+" ms/cycle";
   r.insertCell().textContent=v;
  }
  const ck=await J("/jobs/"+sel+"/checkpoints");
  document.getElementById("ckn").textContent=
   (ck.counts?ck.counts.completed:0)+" completed";
  const kt=document.getElementById("ck");
  while(kt.rows.length>1)kt.deleteRow(1);
  for(const c of(ck.history||[]).slice(-12).reverse()){
   const r=kt.insertRow();
   r.insertCell().textContent=c.id;
   r.insertCell().textContent=c.duration_ms;
   r.insertCell().textContent=c.bytes;
   r.insertCell().textContent=c.entries;
  }
 }catch(e){document.getElementById("err").textContent=String(e)}
}
tick();setInterval(tick,2000);
</script></body></html>"""
