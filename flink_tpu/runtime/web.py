"""Web monitor: JSON status endpoints over the MiniCluster.

The role of flink-runtime-web's WebRuntimeMonitor + handlers (SURVEY §2.9):
a small HTTP server exposing cluster overview, job list/detail, metric
snapshots, and the back-pressure signal (cycle-time percentiles standing in
for the reference's stack-trace sampling, see SURVEY §5: in the micro-batch
design back-pressure IS a growing cycle time).

Endpoints (reference REST shapes, docs/monitoring/rest_api.md):
    /overview                 cluster summary
    /jobs                     job ids + states
    /jobs/<jid>               job detail incl. JobMetrics
    /jobs/<jid>/metrics       full metric snapshot for the job
    /jobs/<jid>/backpressure  cycle-time percentiles
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from flink_tpu.runtime.cluster import MiniCluster


class WebMonitor:
    def __init__(self, cluster: MiniCluster, host: str = "127.0.0.1",
                 port: int = 0):
        self.cluster = cluster
        monitor = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                try:
                    u = urllib.parse.urlsplit(self.path)
                    query = dict(urllib.parse.parse_qsl(u.query))
                    body = monitor._route(u.path, query)
                    code = 200 if body is not None else 404
                    body = body if body is not None else {"error": "not found"}
                except Exception as e:
                    code, body = 500, {"error": str(e)}
                data = json.dumps(body, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="web-monitor"
        )

    def start(self) -> int:
        self._thread.start()
        return self.port

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    # -- routing ---------------------------------------------------------
    def _route(self, path: str, query: Optional[dict] = None) -> Optional[dict]:
        query = query or {}
        if path in ("/", "/overview"):
            jobs = self.cluster.list_jobs()
            return {
                "jobs-running": sum(j["state"] == "RUNNING" for j in jobs),
                "jobs-finished": sum(j["state"] == "FINISHED" for j in jobs),
                "jobs-cancelled": sum(j["state"] == "CANCELED" for j in jobs),
                "jobs-failed": sum(j["state"] == "FAILED" for j in jobs),
                "flink-tpu-version": "0.1",
            }
        if path == "/jobs":
            return {"jobs": self.cluster.list_jobs()}
        m = re.fullmatch(r"/jobs/([^/]+)", path)
        if m:
            try:
                return self.cluster.job_detail(m.group(1))
            except KeyError:
                return None
        m = re.fullmatch(r"/jobs/([^/]+)/metrics", path)
        if m:
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            return rec.env.metric_registry.snapshot()
        m = re.fullmatch(r"/jobs/([^/]+)/state/([^/]+)", path)
        if m:
            from flink_tpu.runtime.queryable import parse_key

            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            if "key" not in query:
                return {"ok": False, "error": "missing ?key="}
            try:
                value = rec.env._kv_registry.query(
                    m.group(2), parse_key(query["key"])
                )
            except KeyError as e:
                return {"ok": False, "error": str(e)}
            return {"ok": True, "value": value}
        m = re.fullmatch(r"/jobs/([^/]+)/backpressure", path)
        if m:
            rec = self.cluster.jobs.get(m.group(1))
            if rec is None:
                return None
            snap = rec.env.metric_registry.snapshot(
                f"jobs.{rec.name}.cycle_time_ms"
            )
            hist = next(iter(snap.values()), {"count": 0})
            count = hist.get("count", 0)
            p99 = hist.get("p99", 0)
            p50 = hist.get("p50", 0) or 1e-9
            # ratio in the spirit of the reference's OK/LOW/HIGH
            # thresholds (BackPressureStatsTracker)
            ratio = min(1.0, (p99 / p50 - 1.0) / 10.0) if count else 0.0
            level = ("ok" if ratio <= 0.10
                     else "low" if ratio <= 0.5 else "high")
            out = {
                "status": "ok",
                "backpressure-level": level,
                "ratio": ratio,
                "cycle-time-ms": hist,
            }
            # cause attribution: measured per-cycle phase decomposition
            # (source-starved / host-bound / device-bound / sink-bound)
            # replacing the reference's stack-trace sampling
            report_fn = getattr(rec.env, "_backpressure_report", None)
            if report_fn is not None:
                out["attribution"] = report_fn()
            # per-phase histograms + end-to-end latency markers
            phases = rec.env.metric_registry.snapshot(
                f"jobs.{rec.name}.phase_"
            )
            if phases:
                out["phase-histograms-ms"] = phases
            lat = rec.env.metric_registry.snapshot(
                f"jobs.{rec.name}.record_latency_ms"
            )
            if lat:
                out["record-latency-ms"] = next(iter(lat.values()))
            return out
        return None
