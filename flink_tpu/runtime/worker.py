"""Worker process — a TaskManager-analog running one job attempt.

The reference runs long-lived TaskManager actors that register with the
JobManager, host task slots and heartbeat over Akka
(TaskManager.scala:296 registration+heartbeats; DeathWatch at :311).
TPU-adapted prototype: a worker is one OS process owning the accelerator
for one job attempt (the per-job container pattern the reference's
YARN/Mesos modes use). It:

  1. registers with the controller over the JSON/TCP control protocol,
  2. heartbeats on an interval (controller marks it dead on timeout OR
     on process exit — the DeathWatch analog),
  3. builds the job from an importable builder reference
     ("pkg.mod:fn" or "path/to/file.py:fn" — the user-code shipping
     seam, ref BlobServer/jar distribution),
  4. executes with checkpointing enabled, restoring from the latest
     checkpoint when respawned after a failure,
  5. reports terminal status back to the controller.

Run: python -m flink_tpu.runtime.worker --controller PORT --worker-id W
     --builder REF --job-name NAME --checkpoint-dir DIR [--restore]
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import os
import sys
import threading
import traceback


def load_builder(ref: str):
    """Resolve "module:function" or "/path/file.py:function"."""
    modpart, _, fnname = ref.rpartition(":")
    if not modpart:
        raise ValueError(f"builder ref {ref!r} must be 'module:function'")
    if modpart.endswith(".py") or os.path.sep in modpart:
        spec = importlib.util.spec_from_file_location("_job_builder", modpart)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
    else:
        mod = importlib.import_module(modpart)
    return getattr(mod, fnname)


def parse_controller(addr: str) -> tuple:
    """'HOST:PORT' (multi-host registration, TaskManager.scala:296) or a
    bare port (single-host back-compat) -> (host, port)."""
    if ":" in addr:
        host, _, port = addr.rpartition(":")
        return host, int(port)
    return "127.0.0.1", int(addr)


def _send(controller: tuple, msg: dict, timeout_s: float = 5.0) -> dict:
    from flink_tpu.runtime.cluster import control_request

    host, port = controller
    return control_request(host, port, msg, timeout_s=timeout_s)


def run_worker(controller, worker_id: str, builder_ref: str,
               job_name: str, checkpoint_dir: str, restore: bool,
               heartbeat_s: float = 0.5) -> int:
    if isinstance(controller, int):
        controller = ("127.0.0.1", controller)
    _send(controller, {
        "action": "register-worker", "worker_id": worker_id,
        "pid": os.getpid(),
        # lets a controller that did not spawn this worker ADOPT it with
        # full context (external TaskManager registration)
        "builder": builder_ref, "job_name": job_name,
        "checkpoint_dir": checkpoint_dir,
    })

    stop = threading.Event()

    def beat():
        while not stop.is_set():
            try:
                _send(controller, {
                    "action": "heartbeat", "worker_id": worker_id,
                })
            except OSError:
                pass          # controller briefly unreachable; keep trying
            stop.wait(heartbeat_s)

    hb = threading.Thread(target=beat, daemon=True, name="worker-heartbeat")
    hb.start()

    status, error = "FINISHED", None
    try:
        builder = load_builder(builder_ref)
        env = builder()
        if checkpoint_dir:
            interval = env.checkpoint_interval_steps or 4
            env.enable_checkpointing(interval, checkpoint_dir)
        restore_from = None
        if restore and checkpoint_dir:
            from flink_tpu.runtime.checkpoint import CheckpointStorage

            st = CheckpointStorage(checkpoint_dir)
            if st.latest() is not None:
                restore_from = checkpoint_dir
        env.execute(job_name, restore_from=restore_from)
    except Exception as e:
        status, error = "FAILED", "".join(
            traceback.format_exception_only(type(e), e)
        ).strip()
    finally:
        stop.set()
        try:
            _send(controller, {
                "action": "worker-status", "worker_id": worker_id,
                "status": status, "error": error,
            })
        except OSError:
            pass
    return 0 if status == "FINISHED" else 1


def main(argv=None) -> int:
    # respect an explicit JAX_PLATFORMS env even where sitecustomize
    # force-dials an accelerator platform (test workers run on the
    # virtual CPU mesh)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
        print(f"[worker] jax_platforms={jax.config.jax_platforms} "
              f"env={plat}", flush=True)

    ap = argparse.ArgumentParser()
    ap.add_argument("--controller", required=True,
                    help="HOST:PORT of the controller (or bare port)")
    ap.add_argument("--worker-id", required=True)
    ap.add_argument("--builder", required=True)
    ap.add_argument("--job-name", default="job")
    ap.add_argument("--checkpoint-dir", default="")
    ap.add_argument("--restore", action="store_true")
    ap.add_argument("--heartbeat-s", type=float, default=0.5)
    a = ap.parse_args(argv)
    return run_worker(parse_controller(a.controller), a.worker_id,
                      a.builder, a.job_name, a.checkpoint_dir, a.restore,
                      a.heartbeat_s)


if __name__ == "__main__":
    sys.exit(main())
