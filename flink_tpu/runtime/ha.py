"""High availability: leader election + HA job registry.

The reference's HA stack is ZooKeeper ephemeral-node leader election
(ZooKeeperLeaderElectionService.java:47), leader retrieval for clients/
TaskManagers, a submitted-job-graph store and a completed-checkpoint
store in ZK so a new leader can recover running jobs
(ZooKeeperCompletedCheckpointStore.java, ZooKeeperSubmittedJobGraphStore).
No ZooKeeper exists in this image; the same contracts are provided over
the filesystem:

  * ``FileLeaderElection`` — an exclusive ``flock`` on a lock file IS
    the leadership (held for the leader's lifetime, like an ephemeral
    node: released automatically when the process dies); the leader
    publishes its address into ``leader.json`` guarded by the lock.
    Standbys block acquiring the lock and are granted leadership when
    the incumbent dies.
  * ``StandaloneLeaderElection`` — always leader (the reference's
    StandaloneLeaderElectionService no-op variant).
  * ``leader_info`` — retrieval side: read the published address
    (LeaderRetrievalService role, used by workers to re-resolve the
    controller after a failover).
  * ``HAJobRegistry`` — durable record of submitted jobs (builder ref,
    checkpoint dir, status) a new leader recovers on takeover
    (SubmittedJobGraphStore role; the completed-checkpoint store role
    is carried by each job's checkpoint directory itself, which the
    restore path already scans for the latest durable checkpoint).

On a shared filesystem this extends to multi-host control-plane HA;
single-host it provides real controller-failover semantics. Wired into
``runtime/process_cluster.py`` (leadership gates the control server; the
job registry drives takeover recovery) and exercised by
``tests/test_process_cluster.py::test_leader_failover_resumes_jobs``,
which SIGKILLs the leader controller process and asserts the standby
finishes its jobs from their latest checkpoints.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading
import time
from typing import Callable, Dict, Optional


class StandaloneLeaderElection:
    """Always leader, no contention (StandaloneLeaderElectionService)."""

    def __init__(self):
        self.is_leader = False

    def start(self, on_grant: Callable[[], None]):
        self.is_leader = True
        on_grant()

    def publish(self, info: dict):
        pass

    def stop(self):
        self.is_leader = False


class FileLeaderElection:
    """flock-based leadership; grant callback fires on acquisition.

    The lock is held until stop() or process death — standbys block in
    a background thread. `publish` writes leader.json (address info)
    only while holding the lock.
    """

    LOCK = "leader.lock"
    INFO = "leader.json"

    def __init__(self, ha_dir: str, contender_id: str):
        self.ha_dir = ha_dir
        self.contender_id = contender_id
        os.makedirs(ha_dir, exist_ok=True)
        self.is_leader = False
        self._fd = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self, on_grant: Callable[[], None]):
        def acquire():
            fd = os.open(
                os.path.join(self.ha_dir, self.LOCK),
                os.O_CREAT | os.O_RDWR, 0o644,
            )
            while not self._stop.is_set():
                try:
                    # block with a timeout-ish poll so stop() can cancel
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    break
                except OSError:
                    time.sleep(0.05)
            if self._stop.is_set():
                os.close(fd)
                return
            self._fd = fd
            self.is_leader = True
            on_grant()

        self._thread = threading.Thread(
            target=acquire, daemon=True,
            name=f"leader-election-{self.contender_id}",
        )
        self._thread.start()

    def publish(self, info: dict):
        if not self.is_leader:
            raise RuntimeError("cannot publish without leadership")
        tmp = os.path.join(self.ha_dir, self.INFO + ".tmp")
        with open(tmp, "w") as f:
            json.dump({**info, "leader_id": self.contender_id,
                       "t": time.time()}, f)
        os.replace(tmp, os.path.join(self.ha_dir, self.INFO))

    def stop(self):
        self._stop.set()
        self.is_leader = False
        if self._fd is not None:
            try:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None


def leader_info(ha_dir: str) -> Optional[dict]:
    """Retrieval side: current published leader address, or None."""
    try:
        with open(os.path.join(ha_dir, FileLeaderElection.INFO)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


class HAJobRegistry:
    """Durable submitted-job records for leader-failover recovery.

    One JSON file per job under <ha_dir>/jobs/, written atomically.
    States: RUNNING (needs a worker) | FINISHED | FAILED | DEAD.
    """

    def __init__(self, ha_dir: str):
        self.dir = os.path.join(ha_dir, "jobs")
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, worker_id: str) -> str:
        return os.path.join(self.dir, f"{worker_id}.json")

    def put(self, worker_id: str, record: Dict):
        tmp = self._path(worker_id) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, self._path(worker_id))

    def update_status(self, worker_id: str, status: str):
        rec = self.get(worker_id)
        if rec is not None:
            rec["status"] = status
            self.put(worker_id, rec)

    def get(self, worker_id: str) -> Optional[Dict]:
        try:
            with open(self._path(worker_id)) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def all(self) -> Dict[str, Dict]:
        out = {}
        for name in os.listdir(self.dir):
            if name.endswith(".json"):
                rec = self.get(name[:-5])
                if rec is not None:
                    out[name[:-5]] = rec
        return out
