"""Sinks (ref: api/functions/sink — print/socket/write/collect)."""

from __future__ import annotations

import json
from typing import Any, Callable, List


class Sink:
    #: set True when invoke_columnar is overridden (vectorized fast path)
    columnar = False
    #: set True when the sink only consumes per-emission AGGREGATES
    #: (count, value sum) and therefore never needs the fired keys/values
    #: transferred off-device. The executor then reduces window fires
    #: on-chip and delivers two scalars per drain instead of O(fires)
    #: bytes over the (slow) device->host link — the TPU-native analog of
    #: a pre-aggregating sink. invoke_reduced() receives the aggregates.
    device_reduce = False

    def open(self):
        pass

    def invoke_batch(self, elements: List[Any]):
        raise NotImplementedError

    def invoke_columnar(self, cols: dict):
        """Vectorized delivery: dict of equal-length numpy arrays."""
        names = list(cols)
        self.invoke_batch(list(zip(*[cols[n] for n in names])))

    def close(self):
        pass

    # -- exactly-once hooks (ref CheckpointedFunction on sinks, e.g.
    # BucketingSink.snapshotState / notifyCheckpointComplete) ------------
    def snapshot_state(self):
        return None

    def restore_state(self, state):
        pass

    def notify_checkpoint_complete(self, checkpoint_id: int):
        pass


class CountingSink(Sink):
    """Benchmark sink: O(1) per batch, tallies count and value sum.

    device_reduce: fired (key, window, value) rows are reduced on-chip and
    only (n, value_sum) cross the wire per drain — results identical to
    the columnar path, minus the per-row transfer."""

    columnar = True
    device_reduce = True

    def __init__(self):
        self.count = 0
        self.value_sum = 0.0

    def invoke_batch(self, elements):
        self.count += len(elements)
        for e in elements:
            v = e[-1] if isinstance(e, tuple) else getattr(e, "value", 0.0)
            self.value_sum += float(v)

    def invoke_columnar(self, cols):
        import numpy as np

        self.count += len(cols["value"])
        self.value_sum += float(np.sum(cols["value"]))

    def invoke_reduced(self, n: int, value_sum: float):
        self.count += int(n)
        self.value_sum += float(value_sum)


class CollectSink(Sink):
    """Test sink gathering all outputs (ref test-utils collect pattern)."""

    def __init__(self):
        self.results: List[Any] = []

    def invoke_batch(self, elements):
        self.results.extend(elements)


class PrintSink(Sink):
    def invoke_batch(self, elements):
        for e in elements:
            print(e)


class FunctionSink(Sink):
    def __init__(self, fn: Callable[[Any], None]):
        self.fn = fn

    def invoke_batch(self, elements):
        for e in elements:
            self.fn(e)


class WriteAsTextSink(Sink):
    def __init__(self, path: str):
        self.path = path
        self._f = None

    def open(self):
        self._f = open(self.path, "w")

    def invoke_batch(self, elements):
        for e in elements:
            self._f.write(f"{e}\n")

    def close(self):
        if self._f:
            self._f.close()


class WriteAsJsonSink(Sink):
    def __init__(self, path: str):
        self.path = path
        self._f = None

    def open(self):
        self._f = open(self.path, "w")

    def invoke_batch(self, elements):
        for e in elements:
            self._f.write(json.dumps(e, default=str) + "\n")

    def close(self):
        if self._f:
            self._f.close()


class QueueSink(Sink):
    """Feedback-edge sink: appends into an iteration head's deque (the role
    of StreamIterationTail pushing into BlockingQueueBroker)."""

    def __init__(self, queue):
        self.queue = queue

    def invoke_batch(self, elements):
        self.queue.extend(elements)


class DiscardingSink(Sink):
    """Swallows output (ref DiscardingSink test util; used by
    asQueryableState where the state itself is the product)."""

    columnar = True

    def invoke_batch(self, elements):
        pass

    def invoke_columnar(self, cols):
        pass
