"""Stage-graph planner: multi-stage keyed windowed DAGs.

The executor historically ran exactly ONE keyed windowed stage per job
(`_translate` collapsed a second keyBy->window pair onto the first and
the leftover shape died much later in a deep NotImplementedError). This
module is the planning half of the round-16 chained-drain subsystem:

  * ``StageGraph.from_pipeline`` collects the ordered
    (KeyByTransformation, WindowAggTransformation) pairs off the
    translated spine and validates the chain SHAPE at setup time — every
    unsupported form raises :class:`StageGraphError` naming the exact
    edge, before any state is allocated or kernel compiled.
  * ``plan_reduces`` / ``plan_specs`` own the per-stage ``ReduceSpec``s
    and downstream ``WindowStageSpec``s (ring sizing, shared key
    layout). Interior stages inherit the upstream key codec unchanged:
    the on-device edge re-keys fires by IDENTITY (the fired 64-bit key
    ids flow straight into the next stage's table), so one host-side
    codec decodes every stage's emissions and a stage-0 ``direct``
    layout remains valid downstream.
  * ``snapshot_chain`` / ``restore_chain`` are the checkpoint cut for
    stages 1..N-1: full logical snapshots that ride the checkpoint's
    aux payload. They are deliberately NOT merged into the incremental
    entries channel — ``replay_chain`` merges entries across a chain by
    (key, pane) and stage-2 rows would collide with stage-1 rows.

The execution half lives in ``runtime/step.py``
(``build_window_chained_drain[_sharded]``): stage-N fire lanes are
packed on device (cumsum + searchsorted + gather — the
``_pack_fire_lanes`` seam) and applied to stage N+1's update inside the
same count-gated drain scan, so an N-stage pipeline still costs one
host dispatch per ring drain. Because the re-key is the identity, fires
stay on their owning shard and the sharded route needs no collective on
the edge.

Exactly-once across the edge needs no in-flight lane payload in the
cut: the chained watermark coupling (``_chain_stage_watermark``) holds
stage N+1's watermark below ``(fired_through_N + 2) * slide_N - 2``, so
every future stage-N fire lands strictly before stage N+1's lateness
horizon — replaying the upstream ring after restore regenerates exactly
the edge traffic the crash lost.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

import numpy as np

from flink_tpu.graph import stream_graph as sg


class StageGraphError(ValueError):
    """A multi-keyed-stage pipeline shape the chained drain cannot run.

    Raised at SETUP time by StageGraph validation with the offending
    edge named — replacing the deep, late NotImplementedError the
    single-stage executor used to throw after silently collapsing the
    extra stages."""


class _Probe:
    """Stand-in WindowResult for probing downstream selectors/extractors."""

    __slots__ = ("key", "window_end_ms", "value")

    def __init__(self, key, value):
        self.key = key
        self.window_end_ms = 0
        self.value = value


@dataclasses.dataclass
class Stage:
    """One keyed windowed stage of the chain (stage 0 = ingest stage)."""

    index: int
    key_by: Optional[sg.KeyByTransformation]
    wagg: sg.WindowAggTransformation

    @property
    def name(self) -> str:
        return f"stage[{self.index}]"

    @property
    def size_ms(self) -> int:
        return self.wagg.assigner.size_ms

    @property
    def slide_ms(self) -> int:
        return self.wagg.assigner.slide_ms


class StageGraph:
    """Validated, topologically ordered chain of keyed windowed stages.

    The spine translation already linearizes the DAG (divergence is
    only legal in trailing stateless chains), so topological order is
    list order; ``edges()`` yields consecutive pairs."""

    def __init__(self, stages: List[Stage]):
        if len(stages) < 2:
            raise StageGraphError(
                "a StageGraph needs at least 2 keyed stages; single-stage "
                "jobs take the direct windowed path"
            )
        self.stages = stages
        self._reduces: Optional[List[Any]] = None

    # ------------------------------------------------------------------
    @classmethod
    def from_pipeline(cls, pipe) -> "StageGraph":
        """Build + shape-validate the graph off a translated pipeline.

        ``pipe.window_agg``/``pipe.key_by`` is stage 0; ``pipe.stages``
        carries the downstream (key_by, wagg) pairs in spine order."""
        if pipe.window_agg is None:
            raise StageGraphError(
                "multi-stage chain has no stage[0] window aggregation "
                "(a downstream keyBy→window pair needs an upstream "
                "windowed stage to consume)"
            )
        stages = [Stage(0, pipe.key_by, pipe.window_agg)]
        for i, (kb, wagg) in enumerate(pipe.stages, start=1):
            if wagg is None:
                raise StageGraphError(
                    f"stage[{i}] has a keyBy with no window aggregation — "
                    f"a downstream keyed stream must end in a window agg "
                    f"(rolling reduces / process functions cannot chain "
                    f"after a windowed stage yet)"
                )
            stages.append(Stage(i, kb, wagg))
        g = cls(stages)
        g.validate()
        return g

    # ------------------------------------------------------------------
    @property
    def depth(self) -> int:
        return len(self.stages)

    def edges(self):
        for up, down in zip(self.stages, self.stages[1:]):
            yield up, down

    def _edge(self, up: Stage, down: Stage) -> str:
        return f"edge {up.name}->{down.name}"

    # ------------------------------------------------------------------
    def validate(self) -> None:
        """Shape validation: every unsupported form names its edge."""
        from flink_tpu.datastream.window.assigners import (
            CountWindowAssigner, GlobalWindows,
        )

        for st in self.stages:
            a = st.wagg.assigner
            where = (st.name if st.index == 0
                     else self._edge(self.stages[st.index - 1], st))
            if isinstance(a, GlobalWindows):
                raise StageGraphError(
                    f"{where}: GlobalWindows cannot participate in a "
                    f"chained stage graph (the generic host window "
                    f"operator runs single-stage only)"
                )
            if isinstance(a, CountWindowAssigner):
                raise StageGraphError(
                    f"{where}: count windows cannot participate in a "
                    f"chained stage graph (count stages run on the host "
                    f"path, single-stage only)"
                )
            if getattr(a, "is_session", False):
                raise StageGraphError(
                    f"{where}: session windows cannot participate in a "
                    f"chained stage graph (sessions run on the host "
                    f"merge path, single-stage only)"
                )
            if not getattr(a, "is_event_time", False):
                raise StageGraphError(
                    f"{where}: chained stages require event-time "
                    f"tumbling/sliding windows"
                )
            if (st.wagg.trigger is not None or st.wagg.evictor is not None
                    or st.wagg.window_fn is not None):
                raise StageGraphError(
                    f"{where}: custom trigger/evictor/window function "
                    f"routes to the generic host operator, which is "
                    f"single-stage only"
                )
            if st.wagg.allowed_lateness_ms:
                raise StageGraphError(
                    f"{where}: allowed lateness is unsupported in a "
                    f"chained stage graph — a late re-fire would re-emit "
                    f"the corrected window into the downstream stage and "
                    f"double-count it"
                )

        for up, down in self.edges():
            e = self._edge(up, down)
            if up.wagg.result_fn is not None:
                raise StageGraphError(
                    f"{e}: {up.name} has a result_fn — host-side result "
                    f"extraction cannot run on an interior edge (fires "
                    f"feed the next stage on device); only the final "
                    f"stage may declare one"
                )
            if down.wagg.value_prep is not None:
                raise StageGraphError(
                    f"{e}: {down.name} has a value_prep — host-side "
                    f"value prep cannot run on an interior edge (the "
                    f"edge carries device fire values directly)"
                )
            self._probe_edge(up, down)

        reduces = self.plan_reduces()
        for up, down in self.edges():
            e = self._edge(up, down)
            r_up, r_down = reduces[up.index], reduces[down.index]
            if r_up.kind == "sketch" or r_down.kind == "sketch":
                raise StageGraphError(
                    f"{e}: sketch reduces cannot sit on a chained edge — "
                    f"register planes are not rollup-able values"
                )
            if tuple(r_down.value_shape) != tuple(r_up.out_shape):
                raise StageGraphError(
                    f"{e}: {down.name} consumes values of shape "
                    f"{tuple(r_down.value_shape)} but {up.name} fires "
                    f"shape {tuple(r_up.out_shape)}"
                )
            if np.dtype(r_down.dtype) != np.dtype(r_up.out_dtype):
                raise StageGraphError(
                    f"{e}: {down.name} consumes dtype "
                    f"{np.dtype(r_down.dtype).name} but {up.name} fires "
                    f"{np.dtype(r_up.out_dtype).name}"
                )

    def _probe_edge(self, up: Stage, down: Stage) -> None:
        """The device edge re-keys by identity and forwards the fire
        value verbatim — the downstream selector/extractor must agree
        (``lambda r: r.key`` / ``lambda r: r.value`` shapes). Probed
        with sentinel objects so a non-conforming lambda fails loudly
        at setup instead of silently computing something else than the
        host-chained semantics."""
        e = self._edge(up, down)
        k_mark, v_mark = object(), object()
        probe = _Probe(k_mark, v_mark)
        try:
            sel = down.key_by.key_selector(probe)
        except Exception as exc:
            raise StageGraphError(
                f"{e}: {down.name}'s key selector failed on a "
                f"WindowResult probe ({exc!r}) — the chained edge "
                f"re-keys by the upstream window key, so the selector "
                f"must be key-preserving (r.key)"
            ) from exc
        if sel is not k_mark:
            raise StageGraphError(
                f"{e}: {down.name}'s key selector does not preserve the "
                f"upstream key — the device edge re-keys fires by "
                f"identity, so only `r.key` selectors are supported"
            )
        if down.wagg.extractor is not None:
            try:
                val = down.wagg.extractor(probe)
            except Exception as exc:
                raise StageGraphError(
                    f"{e}: {down.name}'s value extractor failed on a "
                    f"WindowResult probe ({exc!r}) — the edge carries "
                    f"the fire value verbatim, so the extractor must be "
                    f"`r.value`"
                ) from exc
            if val is not v_mark:
                raise StageGraphError(
                    f"{e}: {down.name}'s value extractor does not pass "
                    f"the upstream fire value through — the device edge "
                    f"forwards it verbatim, so only `r.value` "
                    f"extractors are supported"
                )

    # ------------------------------------------------------------------
    def check_runtime(self, *, use_resident: bool, overflow_lanes: int,
                      drain_stats: bool, reduced_fires: bool,
                      max_stages: int) -> None:
        """Config-dependent validation, called from the executor's
        setup once the pipeline knobs are resolved."""
        if self.depth > max_stages:
            raise StageGraphError(
                f"stage chain depth {self.depth} exceeds "
                f"pipeline.stages.max-stages={max_stages}"
            )
        if not use_resident:
            raise StageGraphError(
                "a chained stage graph requires the resident drain loop "
                "(pipeline.resident-loop must not resolve to off, and "
                "prefetch/device staging must be available) — the edge "
                "exists only inside the drain scan"
            )
        if overflow_lanes:
            raise StageGraphError(
                "the overflow/spill ring is unsupported in a chained "
                "stage graph (spill merges host-side at emission; "
                "interior stages never emit host-side) — set "
                "state.overflow-ring-lanes=0"
            )
        # drain_stats: accepted and supported since ISSUE 17 — the
        # chained drains carry the stage-aware flight recorder, so no
        # rejection; the param stays so the executor's call site reads
        # as the full runtime-knob contract
        del drain_stats
        if reduced_fires:
            raise StageGraphError(
                "device-reduced fire emission (device_reduce sinks) is "
                "unsupported in a chained stage graph — the final "
                "stage's fires emit on the standard compact path"
            )

    # ------------------------------------------------------------------
    def plan_reduces(self) -> List[Any]:
        """Per-stage ReduceSpecs, built once (factories may close over
        mutable user state; calling them once mirrors single-stage
        setup)."""
        if self._reduces is None:
            self._reduces = [s.wagg.reduce_spec_factory()
                             for s in self.stages]
        return self._reduces

    def plan_specs(self, base_spec, drain_depth: int = 1) -> List[Any]:
        """Downstream WindowStageSpecs (stages 1..N-1), derived from the
        resolved stage-0 spec: same capacity/probe/layout (identity
        re-key ⇒ same key population and the same direct-index
        contract), precombine/packed off (edge batches are a few fire
        lanes; the shared-sort and packed-plane seams buy nothing
        there).

        Ring sizing: a downstream stage advances ONCE per drain (the
        chained drain's stage tail), so between advances it must hold
        every pane between its purge horizon and the newest pane a
        just-fired upstream window can land in. A whole drain's worth
        of stage-0 slots fires at most ``drain_depth * F`` upstream
        pane-ends spanning ``drain_depth * F * slide_up`` ticks beyond
        the coupled watermark (the catch-up worst case), on top of the
        usual 2*panes_per_window live span. Ring rows are [C]-sized
        pane planes and the fire eval is O(F * panes_per_window * C) —
        independent of ring length — so the wider ring costs memory,
        not steady-state time."""
        from flink_tpu import ops as _ops  # noqa: F401 (kernel import root)
        from flink_tpu.ops import window_kernels as wk
        from flink_tpu.runtime.step import WindowStageSpec

        reduces = self.plan_reduces()
        specs = []
        for up, down in self.edges():
            size_t, slide_t = down.size_ms, down.slide_ms
            ppw = size_t // slide_t
            f_up = base_spec.win.fires_per_step
            depth = max(1, int(drain_depth))
            slack = (depth * f_up * up.slide_ms) // slide_t + 2
            ring = max(8, 2 * ppw + slack, ppw + 3)
            win = wk.WindowSpec(
                size_ticks=size_t, slide_ticks=slide_t, ring=ring,
                fires_per_step=base_spec.win.fires_per_step,
                lateness_ticks=0, overflow=0,
            )
            specs.append(WindowStageSpec(
                win, reduces[down.index],
                capacity_per_shard=base_spec.capacity_per_shard,
                probe_len=base_spec.probe_len,
                layout=base_spec.layout,
                precombine=False, packed=False,
            ))
        return specs

    # ------------------------------------------------------------------
    # checkpoint cut for stages 1..N-1 (rides the aux payload)
    def snapshot_chain(self, states, specs) -> List[dict]:
        """Full logical snapshots of the downstream stage states, taken
        at the drain boundary (the same cut point as stage 0's). The
        payload is small — C keys x ring panes per stage — so the sync
        fetch rides the checkpoint's SYNC phase like source offsets."""
        from flink_tpu.runtime import checkpoint as ckpt

        out = []
        for st, sp in zip(states, specs):
            entries, scalars = ckpt.snapshot_window_state(
                st, sp.win, red=sp.red
            )
            out.append({
                "entries": entries, "scalars": scalars,
                "size_ticks": int(sp.win.size_ticks),
                "slide_ticks": int(sp.win.slide_ticks),
            })
        return out

    def restore_chain(self, payload, ctx, specs) -> List[Any]:
        """aux['chain_stages'] -> device states for stages 1..N-1."""
        from flink_tpu.runtime import checkpoint as ckpt

        if payload is None or len(payload) != len(specs):
            have = 0 if payload is None else len(payload)
            raise ValueError(
                f"checkpoint carries {have} chained stage snapshot(s) "
                f"but the job declares {len(specs)} downstream stage(s) "
                f"— the stage graph changed across restore"
            )
        states = []
        for i, (ch, sp) in enumerate(zip(payload, specs), start=1):
            if (int(ch["size_ticks"]) != int(sp.win.size_ticks)
                    or int(ch["slide_ticks"]) != int(sp.win.slide_ticks)):
                raise ValueError(
                    f"stage[{i}] window changed across restore: "
                    f"checkpoint has size/slide "
                    f"{ch['size_ticks']}/{ch['slide_ticks']} ticks, job "
                    f"declares {sp.win.size_ticks}/{sp.win.slide_ticks}"
                )
            states.append(ckpt.restore_window_state(
                ch["entries"], ch["scalars"], ctx, sp
            ))
        return states
