"""Cross-host data plane: multi-host keyed windows over ONE global mesh.

The reference's data fabric is every-TaskManager-shuffles-to-every-
TaskManager over TCP (RecordWriter.java:82 feeding Netty subpartitions,
TaskManager.scala:296 registration). The TPU-native redesign
(docs/DCN_INGESTION.md) inverts it:

  * each HOST ingests whatever its source partitions contain (any keys)
    and feeds only its LOCAL devices — records cross the slow network
    once, as ingestion bytes;
  * ONE collective over the global mesh routes every record to the
    device owning its key group (``all_to_all`` for the pane-ring time
    windows, ``all_gather`` + mask for the replicate-and-mask session
    kernel) — the keyed shuffle rides the accelerator fabric (ICI on a
    pod; the cross-process collective transport stands in for it here);
  * control decisions ride the SAME collectives: the global watermark is
    an on-device ``pmin`` of per-host watermarks, and loop termination is
    an on-device conjunction of per-host "source exhausted" flags — so
    every process executes an identical lockstep sequence of compiled
    steps (the SPMD invariant), with no out-of-band consensus protocol.

Round 5 generalizes the plane beyond the original tumbling-sum runner:
sliding windows (any size/slide via the pane ring), session windows
(gap-merged, ``DCNSessionRunner``), any built-in reduce kind, and the
standard ``StreamExecutionEnvironment.execute()`` path selects it when
``dcn.coordinator`` is configured (runtime/executor.py _run_dcn) — the
reference's "same program on every TaskManager" deployment story.

Worker processes join the mesh with ``jax.distributed.initialize``
(the ``--coordinator`` seam the design doc specified); on CPU test
meshes the collectives run over Gloo/TCP, which is exactly the DCN hop
being modeled. Checkpoints are per-process shard snapshots written at a
deterministic lockstep cadence, so a killed ensemble restarts from the
latest cut that EVERY process completed (the reference's
full-job-restart-from-checkpoint failure model, ExecutionGraph restart +
CheckpointCoordinator.restoreLatestCheckpointedState).

Run one worker:
  python -m flink_tpu.runtime.dcn --coordinator H:P --num-processes N
      --process-id K --builder pkg.mod:fn --out result.npz
      [--checkpoint-dir D --ckpt-every C --restore]

``builder()`` returns a DCNJobSpec.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from flink_tpu.runtime import elastic
from flink_tpu.testing import faults

MAX_TICKS = 2**31 - 4


class DCNPeerError(RuntimeError):
    """Attributed data-plane peer failure: the message names WHICH peer
    and WHAT it was doing, so one sick process surfaces as a clean job
    failure instead of an anonymous ensemble hang (the failure-
    containment contract, docs/fault-tolerance.md)."""


class DCNPeerStalledError(DCNPeerError):
    """A live peer stopped sending: the bounded recv deadline expired
    mid-frame. The connect path always had a deadline; this closes the
    steady-state hole where one stalled host wedged every reader."""


class DCNPeerLostError(DCNPeerError, elastic.DeviceLostError):
    """A peer connection reset and bounded reconnect-with-backoff could
    not re-establish the ring — the peer is declared dead.

    Also a :class:`~flink_tpu.runtime.elastic.DeviceLostError`: the
    dead peer's mesh segment (its device) is gone with it, so the
    failure classifies as DEVICE LOSS at the restart boundary. The DCN
    lockstep plane itself cannot re-plan in place (every process bakes
    the global mesh into its collectives), so recovery there is the
    ordinary job-level restart at full parallelism — but the
    classification, metrics, and any supervising controller see the
    loss for what it is."""

    def __init__(self, message: str, lost_shards=(), lost_devices=()):
        elastic.DeviceLostError.__init__(
            self, message, lost_shards=lost_shards,
            lost_devices=lost_devices,
        )


@dataclass
class DCNJobSpec:
    """One keyed windowed aggregation fed from per-host partitions.

    source_factory(process_id, num_processes) -> object with
        poll(max_records) -> (keys int64[n], ts_ms int64[n],
                              values float32[n], exhausted bool)
        snapshot() -> json-able offset state
        restore(state)
    (the per-host slice of the partitioned-consumer contract,
    connectors/partitioned.py / FlinkKafkaConsumerBase.java:65).

    window_kind "time" covers tumbling (slide_ms None/== size_ms) and
    sliding windows; "session" uses gap_ms-merged session windows.
    """

    source_factory: Callable
    size_ms: int = 0
    capacity_per_shard: int = 1 << 16
    max_parallelism: int = 128
    batch_per_host: int = 4096
    fires_per_step: int = 4
    out_of_orderness_ms: int = 0
    reduce_kind: str = "sum"
    slide_ms: Optional[int] = None
    window_kind: str = "time"      # "time" | "session" | "rolling" | "cep"
    # window_kind "cep": a zero-arg factory returning the cep Pattern
    # (factory, not instance: every lockstep process builds its own).
    # The source's VALUE lane carries the per-event stage-match bits
    # packed as a float32 integer (bit s = stage s's predicate; exact
    # for <= 24 stages) — predicates evaluate at the ingesting host, the
    # NFA advances on device, and the base ingest loop stays untouched.
    cep_pattern_factory: Optional[Callable[[], object]] = None
    gap_ms: int = 0                # session gap
    # epoch-ms timestamps exceed int32 ticks: the runner rebases every
    # ts to this origin. A SPEC field (not derived from data) so all
    # lockstep processes agree without coordination; set it to e.g. the
    # job's start-of-day epoch ms for wall-clock sources.
    origin_ms: int = 0
    # physical rebalance (ref RebalancePartitioner.java:30): underfull
    # hosts borrow ingest lanes from their ring neighbor's backlog over a
    # host-to-host TCP side channel, so a skewed partition assignment
    # keeps every host's lane budget busy (see _RebalanceRing). Device-
    # side lane spreading cannot do this — per-host lane counts are fixed
    # by the sharding, so extra ingest capacity must arrive as records
    # over the network, exactly like the reference's rebalance edge.
    rebalance: bool = False
    rebalance_addrs: Optional[list] = None   # "host:port" per process-id
    # host-level ingest partitioner (ref StreamPartitioner catalog,
    # SURVEY §2.11): "forward" (records process on the host whose
    # partition holds them), "rebalance" (deficit-driven neighbor
    # borrowing, equivalent to rebalance=True), "shuffle" (every record
    # routed to a uniformly random host via the targeted ring — the
    # ShufflePartitioner, with per-cycle balanced assignment so no
    # host's lane budget overflows), "global" (every record routed to
    # host 0 — the GlobalPartitioner, whose single-subtask bottleneck
    # cost becomes visible as host-0-bound cycle counts). "rescale" is
    # accepted as an alias of "forward": the reference's rescale keeps
    # records within the local TaskManager group, which is exactly what
    # forward ingestion does here. shuffle/global use the same
    # rebalance_addrs side channel.
    ingest_partitioner: str = "forward"
    # failure containment (docs/fault-tolerance.md): a ring peer that
    # stops sending mid-frame fails ATTRIBUTED after this deadline
    # (DCNPeerStalledError names the peer) instead of wedging the
    # ensemble; a transient peer reset gets this many reconnect
    # attempts (exponential backoff) before DCNPeerLostError.
    peer_recv_timeout_s: float = 120.0
    peer_reconnect_attempts: int = 3
    peer_reconnect_backoff_s: float = 0.25
    # pipeline.steps-per-dispatch plumb-through: the lockstep DCN plane
    # runs ONE poll → route → exchange → update round per collective
    # cycle (every host must dispatch the same step in the same round,
    # and the rebalance/shuffle side channels synchronize per cycle), so
    # K-fused dispatch does not compose with it. Values > 1 take the
    # EXPLICIT single-step fallback: noted loudly at startup, never
    # silently absorbed.
    steps_per_dispatch: int = 1
    # per-host resident mode (pipeline.resident-loop on/while under a
    # dcn.coordinator, ISSUE 20b): between DCN boundaries each host
    # polls up to resident_ring_depth local chunks and retires them in
    # ONE multi-slot drain dispatch (runtime/step.py
    # build_window_dcn_resident_drain — the trip count is pmax-agreed on
    # device, so no host-side count exchange). The rebalance/shuffle/
    # global side channels run ONCE per drain cycle, at the boundary,
    # with their frame deadlines scaled by the slots the previous drain
    # retired (deadline_scale — DCNPeerStalledError attribution keeps
    # its base semantics at scale 1). Time-window jobs only.
    resident: bool = False
    resident_ring_depth: int = 4


class GeneratorPartitionSource:
    """fn(offset, n) -> (keys, ts_ms, values) up to ``total`` records —
    the replayable test/bench partition (deterministic fetch, so offset
    restore gives exactly-once replay)."""

    def __init__(self, fn, total: int):
        self.fn = fn
        self.total = total
        self.offset = 0

    def poll(self, max_records):
        n = min(max_records, self.total - self.offset)
        if n <= 0:
            e = np.zeros(0, np.int64)
            return e, e, np.zeros(0, np.float32), True
        keys, ts, vals = self.fn(self.offset, n)
        self.offset += n
        return (np.asarray(keys, np.int64), np.asarray(ts, np.int64),
                np.asarray(vals, np.float32), self.offset >= self.total)

    def snapshot(self):
        return {"offset": self.offset}

    def restore(self, state):
        self.offset = int(state["offset"])


class _RebalanceRing:
    """Host-level physical rebalance (ref RebalancePartitioner.java:30,
    RecordWriter round-robin edges): each cycle, process p asks its ring
    neighbor (p+1) % nproc to fill p's spare ingest lanes from the
    neighbor's source backlog; records cross hosts as length-prefixed
    numpy frames over TCP — the reference's records-over-the-network
    rebalance, applied at the ingestion edge where this architecture's
    skew cost actually lives (a skewed host needs proportionally more
    lockstep cycles; the keyed all_to_all already balances compute).

    Protocol per cycle:
      1. send REQUEST(my spare lanes) on the next-link,
      2. serve the prev-link: read its spare, poll up to that many extra
         records from MY source, send them (+ my exhausted flag),
      3. read the donation from the next-link into my spare lanes.
    Lockstep safety: every process runs all three phases every cycle.
    Deadlock safety: phase-2 sends happen before anyone's phase-3 read,
    so a donation frame must never need the peer to drain it — frames
    are capped at DONATE_CAP records (≤64 KiB) and both socket buffers
    are raised to hold a full frame, so sendall always completes into
    kernel buffers even when every ring link donates at once (sources
    that trickle below max_records can leave every host with both spare
    lanes AND backlog).

    Failure containment (docs/fault-tolerance.md): steady-state reads
    run in short socket-timeout slices under a ``recv_timeout_s``
    deadline, so a stalled peer raises an attributed
    :class:`DCNPeerStalledError` instead of wedging the reader forever.
    A transient peer RESET triggers a bounded reconnect: both links are
    closed and re-established (the same deterministic dial-next /
    accept-prev dance as startup — a neighbor losing one link resyncs
    its own links too, so the repair cascades around the ring) and the
    ROUND retries from the top. Retry is lossless even when the abort
    is ASYMMETRIC (the donor's round completed while the recipient's
    recv failed): every request frame carries the requester's round
    counter, and the serve side caches its last (round, donation) — a
    re-request for an already-served round re-donates the cached
    records instead of re-polling, so an aborted round's poll is never
    lost and never double-consumed, and nothing is applied to device
    state until the round returns. Reconnect exhaustion raises
    :class:`DCNPeerLostError` naming the peer.
    """

    _REQ = "<IQ"     # spare lane count, requester round counter
    _HDR = "<IB"     # donated record count, donor-exhausted flag
    DONATE_CAP = 3200             # 3200 * 20 B = 62.5 KiB per frame
    _SOCKBUF = 1 << 18            # 256 KiB send/recv buffers
    _SLICE_S = 2.0                # per-I/O socket-timeout slice

    def __init__(self, pid: int, nproc: int, addrs,
                 recv_timeout_s: float = 120.0,
                 reconnect_attempts: int = 3,
                 reconnect_backoff_s: float = 0.25,
                 resync_window_s: float = 30.0):
        import socket
        import struct

        self.struct = struct
        self.socket = socket
        self.pid = pid
        self.nproc = nproc
        self.recv_timeout_s = float(recv_timeout_s)
        # drain-boundary deadline scaling (per-host resident mode, ISSUE
        # 20b): the runner sets this to the slot count the PREVIOUS
        # drain retired, so a peer legitimately busy draining a deep
        # ring gets proportionally more frame time before
        # DCNPeerStalledError attributes it — same contract as
        # Watchdog.arm(scale=), never below the configured base deadline
        self.deadline_scale = 1.0
        self.reconnect_attempts = max(0, int(reconnect_attempts))
        self.reconnect_backoff_s = float(reconnect_backoff_s)
        # how long a resync waits for the lost peer to come back up
        # (redial + re-accept window); bounded so a peer that is gone
        # for good attributes instead of redialing forever
        self.resync_window_s = float(resync_window_s)
        if not addrs or len(addrs) != nproc:
            raise ValueError(
                "rebalance requires rebalance_addrs with one host:port "
                "per process"
            )
        self.addrs = list(addrs)
        # asymmetric-retry protection (see class docstring): my round
        # counter stamps every request; the serve side remembers the
        # last round it donated for so a RE-request re-donates
        self._round = 0
        self._served_round = None
        self._served_cache = None
        host, port = addrs[pid].rsplit(":", 1)
        # the listen socket stays open for the ring's lifetime: a reset
        # link re-ACCEPTS through it (reconnect support), exactly like
        # the initial handshake
        self._srv = socket.create_server((host, int(port)))
        self.next_sock = None
        self.prev_sock = None
        self._dial_next(120.0)
        self._accept_prev(120.0)

    # -- link plumbing --------------------------------------------------
    def _peer(self, which: str) -> int:
        return (self.pid + (1 if which == "next" else -1)) % self.nproc

    def _sock_opts(self, s):
        # short slices so the recv loop can enforce the overall deadline
        # (and deliver async cancellation) without OS-level blocking
        s.settimeout(min(self._SLICE_S, max(0.05, self.recv_timeout_s)))
        s.setsockopt(self.socket.SOL_SOCKET, self.socket.SO_SNDBUF,
                     self._SOCKBUF)
        s.setsockopt(self.socket.SOL_SOCKET, self.socket.SO_RCVBUF,
                     self._SOCKBUF)

    def _dial_next(self, window_s: float):
        nhost, nport = self.addrs[self._peer("next")].rsplit(":", 1)
        deadline = time.monotonic() + window_s
        self.next_sock = None
        while self.next_sock is None:
            try:
                self.next_sock = self.socket.create_connection(
                    (nhost, int(nport)), timeout=5
                )
            except OSError:
                if time.monotonic() > deadline:
                    raise DCNPeerLostError(
                        f"process {self.pid}: peer {self._peer('next')} "
                        f"({nhost}:{nport}) unreachable for "
                        f"{window_s:.0f}s"
                    )
                time.sleep(0.1)
        self._sock_opts(self.next_sock)

    def _accept_prev(self, window_s: float):
        self._srv.settimeout(window_s)
        try:
            self.prev_sock, _ = self._srv.accept()
        except self.socket.timeout:
            raise DCNPeerLostError(
                f"process {self.pid}: peer {self._peer('prev')} did not "
                f"redial within {window_s:.0f}s"
            ) from None
        self._sock_opts(self.prev_sock)

    def _resync(self):
        """Close and re-establish BOTH links. A neighbor that lost only
        one link observes OUR close on the other and resyncs too, so the
        repair cascades around the ring; fresh sockets also discard any
        half-frame bytes of the aborted round."""
        for s in (self.next_sock, self.prev_sock):
            try:
                s.close()
            except OSError:
                pass
        self._dial_next(self.resync_window_s)
        self._accept_prev(self.resync_window_s)

    def _run_round(self, fn, attempts: Optional[int] = None):
        """Run one ring round; on a transient connection failure, resync
        links (bounded, backed off) and retry the whole round. Lossless
        by construction: the serve side re-donates its cached records on
        a round re-request (see exchange) and callers apply nothing
        until the round returns. Stall deadlines do NOT retry — a
        stalled-but-connected peer is attributed, not waited out
        twice."""
        attempts = self.reconnect_attempts if attempts is None else attempts
        for attempt in range(attempts + 1):
            try:
                return fn()
            except DCNPeerError:
                raise
            except (ConnectionError, OSError) as e:
                if isinstance(e, self.socket.timeout):
                    raise      # sends/recvs convert slices to deadlines
                if attempt >= attempts:
                    raise DCNPeerLostError(
                        f"process {self.pid}: ring peer lost and "
                        f"{attempts} reconnect attempt(s) failed: {e}"
                    ) from e
                time.sleep(self.reconnect_backoff_s * (2 ** attempt))
                self._resync()
        raise AssertionError("unreachable")

    def _frame_deadline_s(self) -> float:
        """The live frame deadline: base recv timeout scaled by the
        slot count the previous resident drain retired (1.0 in lockstep
        single-step mode, so behavior there is byte-identical)."""
        return self.recv_timeout_s * max(1.0, float(self.deadline_scale))

    def _send_all(self, sock, data: bytes, peer: str = "peer") -> None:
        """sendall in socket-timeout slices under the SAME deadline the
        reads get: a peer that merely pauses (checkpoint sync, GC) while
        our frame overruns the kernel buffers is waited out up to
        ``recv_timeout_s`` (drain-scaled), then attributed — never
        killed on one 2-second slice."""
        frame_s = self._frame_deadline_s()
        deadline = time.monotonic() + frame_s
        view = memoryview(data)
        sent = 0
        while sent < len(view):
            try:
                sent += sock.send(view[sent:])
            except self.socket.timeout:
                if time.monotonic() >= deadline:
                    raise DCNPeerStalledError(
                        f"process {self.pid}: peer {peer} stalled — "
                        f"send stuck at {sent}/{len(view)} frame bytes "
                        f"after {frame_s:.1f}s"
                    ) from None
                continue

    def _recv_exact(self, sock, n: int, peer: str = "peer") -> bytes:
        # ONE injection hit per FRAME read (outside the slice loop):
        # occurrence-indexed rules stay deterministic regardless of how
        # many empty timeout slices the scheduler happens to produce
        faults.inject("dcn.recv", pid=self.pid, peer=peer, sock=sock)
        buf = b""
        frame_s = self._frame_deadline_s()
        deadline = time.monotonic() + frame_s
        while len(buf) < n:
            try:
                chunk = sock.recv(n - len(buf))
            except self.socket.timeout:
                if time.monotonic() >= deadline:
                    raise DCNPeerStalledError(
                        f"process {self.pid}: peer {peer} stalled — "
                        f"{len(buf)}/{n} frame bytes after "
                        f"{frame_s:.1f}s"
                    ) from None
                continue
            if not chunk:
                raise ConnectionResetError(
                    f"rebalance peer {peer} closed the link"
                )
            buf += chunk
        return buf

    def _serve_donation(self, want: int, req_round: int, poll_extra):
        """Serve one request, re-donating from the cache when the peer
        RE-requests a round we already served (its recv of our donation
        failed): the polled records went into a dead socket, not into
        the peer — re-donating them is what makes asymmetric-abort
        retry lossless; a NEW round always polls fresh."""
        if req_round == self._served_round and self._served_cache is not None:
            return self._served_cache
        donation = poll_extra(want) if want else (
            np.zeros(0, np.int64), np.zeros(0, np.int64),
            np.zeros(0, np.float32), False,
        )
        self._served_round = req_round
        self._served_cache = donation
        return donation

    def exchange(self, spare: int, poll_extra):
        """One rebalance round. ``poll_extra(n)`` polls up to n records
        from this host's source, returning (keys, ts_ms, vals,
        exhausted). Returns (keys, ts_ms, vals, donor_done) received into
        this host's spare lanes."""
        st = self.struct

        def round_once():
            faults.inject("dcn.send", pid=self.pid, link="next",
                          sock=self.next_sock)
            self._send_all(
                self.next_sock, st.pack(self._REQ, int(spare), self._round),
                peer=f"next/{self._peer('next')}",
            )
            # serve the prev neighbor
            want, req_round = st.unpack(
                self._REQ,
                self._recv_exact(self.prev_sock, st.calcsize(self._REQ),
                                 peer=f"prev/{self._peer('prev')}"),
            )
            want = min(int(want), self.DONATE_CAP)
            keys, ts, vals, done = self._serve_donation(
                want, req_round, poll_extra
            )
            n = len(keys)
            self._send_all(
                self.prev_sock,
                st.pack(self._HDR, n, 1 if done else 0)
                + np.asarray(keys, np.int64).tobytes()
                + np.asarray(ts, np.int64).tobytes()
                + np.asarray(vals, np.float32).tobytes(),
                peer=f"prev/{self._peer('prev')}",
            )
            # collect my donation
            hdr = self._recv_exact(
                self.next_sock, st.calcsize(self._HDR),
                peer=f"next/{self._peer('next')}",
            )
            m, ddone = st.unpack(self._HDR, hdr)
            payload = self._recv_exact(
                self.next_sock, m * (8 + 8 + 4),
                peer=f"next/{self._peer('next')}",
            )
            rk = np.frombuffer(payload[: 8 * m], np.int64)
            rt = np.frombuffer(payload[8 * m: 16 * m], np.int64)
            rv = np.frombuffer(payload[16 * m:], np.float32)
            return rk, rt, rv, bool(ddone)

        out = self._run_round(round_once)
        self._round += 1
        return out

    def close(self):
        for s in (self.next_sock, self.prev_sock, self._srv):
            try:
                if s is not None:
                    s.close()
            except OSError:
                pass


class _TargetRing(_RebalanceRing):
    """Targeted ring router for the shuffle/global ingest partitioners
    (ref ShufflePartitioner.java / GlobalPartitioner.java): each cycle,
    every host stamps its polled records with a destination host and the
    ring relays frames ``nproc - 1`` hops (records flow p+1 -> p, the
    donation direction the sockets already run), so every record sits at
    its destination before the cycle's device step. Routing completes
    WITHIN the cycle — no cross-cycle in-flight records — so the
    cycle-boundary checkpoint cut stays a consistent exactly-once
    barrier without any new snapshot state.

    Termination: every frame carries the sender's accumulated
    all-sources-exhausted flag; after ``nproc - 1`` hops the AND covers
    the whole ring, and a host is done once that holds and it ingested
    nothing this cycle (the device-side stop conjunction still gates the
    ensemble, as for forward ingestion).

    Frames are (count, done, targets u8[n], keys i64[n], ts i64[n],
    vals f32[n]); the caller bounds per-cycle polls so the merged inflow
    never exceeds the lane budget (see _DCNRunnerBase._poll_budget).
    """

    def route(self, keys, ts_ms, vals, targets, exhausted: bool):
        """Returns (keys, ts_ms, vals, all_done) of the records whose
        destination is this host. The multi-hop relay is NOT retried on
        a reset: unlike the pairwise exchange (whose re-donation cache
        makes retry lossless), a host whose relay round COMPLETED while
        a neighbor's failed would see the neighbor's re-relayed records
        as next-round traffic and deliver duplicates — so a targeted-
        ring reset fails attributed (DCNPeerLostError) and recovery is
        the job-level restart-from-checkpoint path. Reads and sends
        still run under the stall deadline."""
        st = self.struct

        def round_once():
            mine_k, mine_t, mine_v = [], [], []

            def split(k, t, v, tgt):
                here = tgt == self.pid
                if here.any():
                    mine_k.append(k[here])
                    mine_t.append(t[here])
                    mine_v.append(v[here])
                away = ~here
                return k[away], t[away], v[away], tgt[away]

            pk, pt, pv, ptgt = split(
                np.asarray(keys, np.int64), np.asarray(ts_ms, np.int64),
                np.asarray(vals, np.float32), np.asarray(targets, np.uint8),
            )
            all_done = bool(exhausted)
            for _hop in range(self.nproc - 1):
                n = len(pk)
                faults.inject("dcn.send", pid=self.pid, link="prev",
                              sock=self.prev_sock)
                self._send_all(
                    self.prev_sock,
                    st.pack(self._HDR, n, 1 if all_done else 0)
                    + ptgt.tobytes() + pk.tobytes() + pt.tobytes()
                    + pv.tobytes(),
                    peer=f"prev/{self._peer('prev')}",
                )
                hdr = self._recv_exact(
                    self.next_sock, st.calcsize(self._HDR),
                    peer=f"next/{self._peer('next')}",
                )
                m, done_flag = st.unpack(self._HDR, hdr)
                payload = self._recv_exact(
                    self.next_sock, m * (1 + 8 + 8 + 4),
                    peer=f"next/{self._peer('next')}",
                )
                rtgt = np.frombuffer(payload[:m], np.uint8)
                rk = np.frombuffer(payload[m: m + 8 * m], np.int64)
                rt = np.frombuffer(payload[m + 8 * m: m + 16 * m],
                                   np.int64)
                rv = np.frombuffer(payload[m + 16 * m:], np.float32)
                all_done = all_done and bool(done_flag)
                pk, pt, pv, ptgt = split(rk, rt, rv, rtgt)
            if len(pk):
                raise RuntimeError(
                    f"{len(pk)} record(s) undeliverable after "
                    f"{self.nproc - 1} ring hops (bad target?)"
                )
            if mine_k:
                return (np.concatenate(mine_k), np.concatenate(mine_t),
                        np.concatenate(mine_v), all_done)
            return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.float32), all_done)

        return self._run_round(round_once, attempts=0)


class _DCNRunnerBase:
    """One process's half of a lockstep multi-host keyed job: global-mesh
    setup, the ingest/step/emit loop, and checkpoint/restore. Subclasses
    compile the stage step (``_build_step`` setting ``self._step``) and
    decode its per-shard fire outputs (``_emit_local``). The step
    contract: step(state, hi, lo, ts, values, valid, wm, done) ->
    (state, aux, stop) with stop an all-shards-identical int32."""

    def __init__(self, spec: DCNJobSpec, process_id: int,
                 num_processes: int,
                 checkpoint_dir: Optional[str] = None,
                 ckpt_every: int = 0, restore: bool = False):
        import jax

        self.spec = spec
        self.pid = process_id
        self.nproc = num_processes
        self.ckpt_dir = checkpoint_dir
        self.ckpt_every = ckpt_every
        self.want_restore = restore
        self.source = spec.source_factory(process_id, num_processes)
        # emitted (key_id, window_start_ms, window_end_ms, value)
        self.rows_key = []
        self.rows_start = []
        self.rows_end = []
        self.rows_val = []
        self._persisted_chunks = 0   # rows chunks already in a checkpoint
        self.cycle = 0
        self._next_cid = 1

        from flink_tpu.parallel.mesh import MeshContext

        self.n = len(jax.devices())
        self.L = len(jax.local_devices())
        if self.n != self.L * num_processes:
            raise RuntimeError(
                f"expected {self.L}x{num_processes} global devices, "
                f"got {self.n}"
            )
        self.ctx = MeshContext.create(self.n, spec.max_parallelism)
        # per-host lane budget, one equal slice per local device
        self.B_local = max(self.L, (spec.batch_per_host // self.L) * self.L)
        mode = spec.ingest_partitioner
        if spec.rebalance:
            mode = "rebalance"
        ring_kw = dict(
            recv_timeout_s=spec.peer_recv_timeout_s,
            reconnect_attempts=spec.peer_reconnect_attempts,
            reconnect_backoff_s=spec.peer_reconnect_backoff_s,
        )
        if mode in ("forward", "rescale") or num_processes == 1:
            self._ring, self._router = None, None
        elif mode == "rebalance":
            self._ring = _RebalanceRing(process_id, num_processes,
                                        spec.rebalance_addrs, **ring_kw)
            self._router = None
        elif mode in ("shuffle", "global"):
            self._ring = None
            self._router = _TargetRing(process_id, num_processes,
                                       spec.rebalance_addrs, **ring_kw)
        else:
            raise ValueError(
                f"unknown ingest_partitioner {mode!r} (forward | rescale "
                f"| rebalance | shuffle | global)")
        self._mode = mode
        if spec.steps_per_dispatch > 1:
            # explicit single-step fallback (never silent): fused
            # dispatch would hold batches across collective rounds, but
            # every host must enter the same all_to_all in the same
            # round — a host with a full slot and a host with a partial
            # one would deadlock the lockstep
            print(
                f"[dcn] pipeline.steps-per-dispatch="
                f"{spec.steps_per_dispatch} does not apply to the "
                f"lockstep DCN plane; running single-step dispatch",
                file=sys.stderr,
            )
        self.ingested_local = 0   # records this host's lanes carried
        # per-host resident mode (ISSUE 20b): subclasses that support it
        # set self._drain + self._resident_depth in _build_step
        self._drain = None
        self._resident_depth = 0
        self._build_step()
        if getattr(spec, "resident", False) and self._drain is None:
            raise ValueError(
                "DCNJobSpec.resident requires a time-window job "
                "(window_kind='time'); session/rolling/cep runners keep "
                "single-step lockstep dispatch"
            )
        self._init_state()

    # -- mesh plumbing ----------------------------------------------------
    def _mk_lane_sharding(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from flink_tpu.parallel.mesh import SHARD_AXIS

        self._lane_sharding = NamedSharding(mesh, P(SHARD_AXIS))
        # slot-major stacks for the resident drain: [depth, B] with the
        # slot axis replicated and the lane axis process-sharded
        self._slot_sharding = NamedSharding(mesh, P(None, SHARD_AXIS))

    def _init_state(self):
        self.state = self._init_fn()
        self.local_wm_ticks = -(2**31) + 1
        if self.want_restore and self.ckpt_dir:
            self._restore_latest()

    def _global(self, local: np.ndarray):
        """Assemble a global [nproc*B_local] mesh-sharded array from this
        process's local lanes (jax.make_array_from_process_local_data:
        the host→local-device feed of the ingestion design)."""
        import jax

        return jax.make_array_from_process_local_data(
            self._lane_sharding, local
        )

    # -- ingest partitioning ----------------------------------------------
    def _poll_budget(self) -> int:
        """Per-cycle source poll bound. Routed modes bound the MERGED
        inflow by the lane budget: global concentrates every host's poll
        on host 0 (sum of polls <= B), shuffle's balanced per-donor split
        hands each receiver at most ceil(poll/nproc) per donor (sum <= B
        after the nproc safety margin). A frame must also fit the ring
        sockets' buffers so sendall can't deadlock the lockstep."""
        B = self.B_local
        if self._router is None:
            return B
        frame_cap = _RebalanceRing._SOCKBUF // 32   # ~21 B/record + slack
        if self._mode == "global":
            return max(1, min(B // self.nproc, frame_cap))
        return max(1, min(B - self.nproc, frame_cap))

    def _targets(self, n: int) -> np.ndarray:
        """Destination host per polled record. shuffle: a balanced random
        assignment — each record's destination is uniform, each cycle's
        per-donor counts are equal to within one, so lane budgets hold
        (the reference's ShufflePartitioner draws per record and relies
        on elastic buffers; fixed lane budgets need the balance).
        global: everything to host 0 (GlobalPartitioner.java)."""
        if self._mode == "global":
            return np.zeros(n, np.uint8)
        # modulo in int64: uint8 arange wraps at 256, which would skew
        # the per-target counts past the lane-budget margin for any
        # nproc that doesn't divide 256
        base = (np.arange(n, dtype=np.int64) % self.nproc).astype(np.uint8)
        rng = np.random.default_rng((self.pid, self.cycle))
        return rng.permutation(base)

    # -- host loop ---------------------------------------------------------
    def _poll_chunk(self, exhausted: bool, exchange: bool = True):
        """One padded ingest chunk: poll the source, run the ring /
        router side channels when ``exchange`` (the DCN boundary —
        resident mode's follow-up chunks stay host-local), pad to the
        lane budget and advance the local watermark. Returns ``(hi, lo,
        ts, values, valid, m, done_now, exhausted)``."""
        from flink_tpu.ops.hashing import key_identity64

        spec = self.spec
        B = self.B_local
        poll_budget = self._poll_budget()
        if not exhausted:
            keys, ts_ms, vals, exhausted = self.source.poll(poll_budget)
        else:
            keys = np.zeros(0, np.int64)
            ts_ms = np.zeros(0, np.int64)
            vals = np.zeros(0, np.float32)
        done_now = exhausted
        if self._router is not None:
            # targeted routing (shuffle/global): stamp destinations,
            # relay around the ring, ingest what lands here. The
            # per-host watermark advances from the SOURCE's (pre-
            # route) timestamps: the routed mix contains other
            # hosts' later timestamps, and a watermark read off the
            # merged batch would push the global pmin past records a
            # slower source hasn't polled yet (late-dropping them).
            # Source-side watermarks keep pmin = the true low mark.
            if len(ts_ms):
                rel_max = int(np.asarray(  # host-sync-ok: source-poll numpy, no device array
                    ts_ms, np.int64).max()) \
                    - spec.origin_ms
                self.local_wm_ticks = min(max(
                    self.local_wm_ticks,
                    rel_max - spec.out_of_orderness_ms - 1,
                ), MAX_TICKS)
            if exchange:
                keys, ts_ms, vals, all_done = self._router.route(
                    keys, ts_ms, vals,
                    self._targets(len(keys)), exhausted,
                )
                done_now = all_done and len(keys) == 0
            else:
                # resident follow-up chunk: the records stay on the
                # polling host's lanes (the device all_to_all still
                # delivers each to the owning shard, so results are
                # unchanged — host-level placement waits for the next
                # boundary), and peer done flags are only learned at
                # boundaries
                done_now = False
        if self._ring is not None:
            if exchange:
                # physical rebalance: offer spare lanes to the ring
                # neighbor's backlog, serve the other neighbor's
                # request from MY backlog (every process, every
                # boundary — lockstep)
                rk, rt, rv, donor_done = self._ring.exchange(
                    B - len(keys), self.source.poll
                )
                if len(rk):
                    keys = np.concatenate([keys, rk])
                    ts_ms = np.concatenate([ts_ms, rt])
                    vals = np.concatenate([vals, rv])
                # keep cycling while the donor neighbor has records
                done_now = exhausted and donor_done and not len(rk)
            else:
                done_now = False
        m = len(keys)
        self.ingested_local += m
        h = key_identity64(keys) if m else np.zeros(0, np.uint64)
        hi = np.zeros(B, np.uint32)
        lo = np.zeros(B, np.uint32)
        hi[:m] = (h >> np.uint64(32)).astype(np.uint32)
        lo[:m] = (h & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        ts = np.zeros(B, np.int32)
        if m:
            rts = np.asarray(  # host-sync-ok: source-poll numpy, no device array
                ts_ms, np.int64) - spec.origin_ms
            if int(rts.max()) > MAX_TICKS or int(rts.min()) < 0:
                # refuse rather than silently clamp (clamped records
                # would all collapse into the MAX_TICKS window)
                bad = (int(rts.min()) if int(rts.min()) < 0
                       else int(rts.max()))
                raise ValueError(
                    f"timestamp {bad + spec.origin_ms} out of int32 "
                    f"tick range relative to origin_ms="
                    f"{spec.origin_ms}; set DCNJobSpec.origin_ms to "
                    f"(at most) the stream's first timestamp"
                )
            ts[:m] = rts.astype(np.int32)
        values = np.zeros(B, np.float32)
        values[:m] = vals
        valid = np.zeros(B, bool)
        valid[:m] = True
        if m and self._router is None:
            # routed modes advanced the watermark pre-route (above)
            self.local_wm_ticks = min(max(
                self.local_wm_ticks,
                int(rts.max()) - spec.out_of_orderness_ms - 1,
            ), MAX_TICKS)
        return hi, lo, ts, values, valid, m, done_now, exhausted

    def run(self) -> dict:
        if getattr(self.spec, "resident", False):
            return self._run_resident()
        exhausted = False
        while True:
            (hi, lo, ts, values, valid, _m, done_now,
             exhausted) = self._poll_chunk(exhausted)
            wm_now = MAX_TICKS if done_now else self.local_wm_ticks
            wm = np.full(self.L, np.int32(wm_now))
            done = np.full(self.L, np.int32(1 if done_now else 0))

            self.state, aux, stop = self._step(
                self.state, self._global(hi), self._global(lo),
                self._global(ts), self._global(values), self._global(valid),
                self._global(wm), self._global(done),
            )
            self._emit_local(aux)
            self.cycle += 1
            # NO exhausted gate: with unequal partitions one host drains
            # early, and gating on the local flag would leave the ensemble
            # unable to ever complete another checkpoint (a drained
            # source's offset snapshot is simply its final offset)
            if self.ckpt_dir and self.ckpt_every and \
                    self.cycle % self.ckpt_every == 0:
                self._write_checkpoint()
            if int(np.asarray(stop)) == 1:  # host-sync-ok: lockstep stop decision, one fetch per dispatch
                break
        return self._finish()

    def _run_resident(self) -> dict:
        """Per-host resident mode (ISSUE 20b): each cycle polls up to
        ``resident_ring_depth`` chunks — the FIRST runs the DCN
        side-channel exchange (the drain boundary); follow-ups stay
        host-local — and retires them all in ONE drain dispatch.
        Stop / watermark / fill agreement ride the drain kernel's
        collectives, and the side channels' frame deadlines scale with
        the slots the previous drain retired (a host deep in a long
        drain is making progress, not stalled)."""
        drain = self._drain   # __init__ guarantees this for resident specs
        B = self.B_local
        D = self._resident_depth
        exhausted = False
        drained_prev = 1
        while True:
            for ch in (self._ring, self._router):
                if ch is not None:
                    ch.deadline_scale = max(1.0, float(drained_prev))
            hi_s = np.zeros((D, B), np.uint32)
            lo_s = np.zeros((D, B), np.uint32)
            ts_s = np.zeros((D, B), np.int32)
            val_s = np.zeros((D, B), np.float32)
            ok_s = np.zeros((D, B), bool)
            wm_s = np.empty((D, self.L), np.int32)
            fill = 0
            done_now = False
            for ci in range(D):
                (hi, lo, ts, values, valid, m, done_now,
                 exhausted) = self._poll_chunk(exhausted, exchange=ci == 0)
                hi_s[fill], lo_s[fill], ts_s[fill] = hi, lo, ts
                val_s[fill], ok_s[fill] = values, valid
                wm_s[fill] = np.int32(
                    MAX_TICKS if done_now else self.local_wm_ticks)
                fill += 1
                if done_now or m == 0:
                    # a dry local poll ends the cycle early: padding the
                    # drain with empty slots buys nothing, and the next
                    # boundary may land records from peers
                    break
            wm_s[fill:] = wm_s[fill - 1]  # pad slots hold the frontier
            done = np.full(self.L, np.int32(1 if done_now else 0))
            fills = np.full(self.L, np.int32(fill))
            self.state, cfs, stop, drained = drain(
                self.state,
                self._gslots(hi_s), self._gslots(lo_s),
                self._gslots(ts_s), self._gslots(val_s),
                self._gslots(ok_s), self._gslots(wm_s),
                self._global(done), self._global(fills),
            )
            drained_prev = int(np.asarray(drained))  # host-sync-ok: drain boundary — the agreed count scales the next frame deadline
            self._emit_local_slots(cfs, drained_prev)
            self.cycle += 1
            if self.ckpt_dir and self.ckpt_every and \
                    self.cycle % self.ckpt_every == 0:
                self._write_checkpoint()
            if int(np.asarray(stop)) == 1:  # host-sync-ok: lockstep stop decision, one fetch per dispatch
                break
        return self._finish()

    def _gslots(self, local: np.ndarray):
        """Assemble a [depth, B_local] host stack into the global
        [depth, B] slot-major array (slot axis replicated, lane axis
        sharded across processes)."""
        import jax

        return jax.make_array_from_process_local_data(
            self._slot_sharding, local
        )

    def _finish(self) -> dict:
        if self._ring is not None:
            self._ring.close()
        if self._router is not None:
            self._router.close()
        return {
            "key_id": (np.concatenate(self.rows_key)
                       if self.rows_key else np.zeros(0, np.uint64)),
            "window_start_ms": (np.concatenate(self.rows_start)
                                if self.rows_start
                                else np.zeros(0, np.int64)),
            "window_end_ms": (np.concatenate(self.rows_end)
                              if self.rows_end else np.zeros(0, np.int64)),
            "value": (np.concatenate(self.rows_val)
                      if self.rows_val else np.zeros(0, np.float32)),
            "cycles": self.cycle,
            "ingested_local": self.ingested_local,
            "dropped_capacity": self._state_dropped(),
        }

    def _state_dropped(self) -> int:
        """Sum the device state's drop counter over THIS process's
        shards. The counter lives in the checkpointed state (exchange
        overflow + table-full drops fold into it inside the step), so
        it survives kill-recover — a run that lost records can never
        report an affirmative zero."""
        # no silent-zero guard: a runner state without the counter is a
        # bug, and reporting an affirmative 0 for it would be exactly the
        # false assurance this accessor exists to prevent
        dc = self.state.dropped_capacity
        return int(sum(
            np.asarray(s.data).sum() for s in dc.addressable_shards
        ))

    # -- checkpoint / restore ---------------------------------------------
    # Deterministic lockstep cadence: every process reaches cycle k
    # together, so "all P proc files for cid exist" is a consistent global
    # cut (the step boundary IS the barrier, SURVEY §3.4).
    def _write_checkpoint(self):
        import jax

        cid = self._next_cid
        # fault seam: a raising rule models a process crashing mid-cut;
        # the lockstep cadence means the ensemble's OTHER procs publish
        # their halves and restore skips the globally-incomplete cid
        faults.inject("dcn.ckpt.write", pid=self.pid, cid=cid)
        d = os.path.join(self.ckpt_dir, f"chk-{cid:06d}")
        os.makedirs(d, exist_ok=True)
        leaves, _ = jax.tree_util.tree_flatten(self.state)
        arrs = {}
        for i, leaf in enumerate(leaves):
            shards = sorted(leaf.addressable_shards,
                            key=lambda s: s.index[0].start or 0)
            arrs[f"leaf{i}"] = np.concatenate(
                [np.asarray(s.data) for s in shards], axis=0
            )
        # emission DELTA since the previous checkpoint: each checkpoint is
        # O(new rows), and restore replays the deltas in cid order (the
        # per-checkpoint sink-offset pattern of runtime/checkpoint.py)
        dk = self.rows_key[self._persisted_chunks:]
        ds = self.rows_start[self._persisted_chunks:]
        de = self.rows_end[self._persisted_chunks:]
        dv = self.rows_val[self._persisted_chunks:]
        arrs["rows_key"] = (np.concatenate(dk) if dk
                            else np.zeros(0, np.uint64))
        arrs["rows_start"] = (np.concatenate(ds) if ds
                              else np.zeros(0, np.int64))
        arrs["rows_end"] = (np.concatenate(de) if de
                            else np.zeros(0, np.int64))
        arrs["rows_val"] = (np.concatenate(dv) if dv
                            else np.zeros(0, np.float32))
        tmpf = tempfile.NamedTemporaryFile(
            dir=d, prefix=f"proc-{self.pid}.", suffix=".tmp", delete=False
        )
        np.savez(tmpf, **arrs)
        tmpf.close()
        os.replace(tmpf.name, os.path.join(d, f"proc-{self.pid}.npz"))
        meta = {
            "cycle": self.cycle,
            "wm_ticks": self.local_wm_ticks,
            "source": self.source.snapshot(),
            "next_cid": cid + 1,
        }
        tmp = os.path.join(d, f"proc-{self.pid}.meta.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(d, f"proc-{self.pid}.meta.json"))
        self._next_cid = cid + 1
        self._persisted_chunks = len(self.rows_key)

    def _latest_complete(self) -> Optional[str]:
        if not os.path.isdir(self.ckpt_dir):
            return None
        best = None
        for name in sorted(os.listdir(self.ckpt_dir)):
            if not name.startswith("chk-"):
                continue
            d = os.path.join(self.ckpt_dir, name)
            if all(
                os.path.exists(os.path.join(d, f"proc-{p}.meta.json"))
                for p in range(self.nproc)
            ):
                best = d
        return best

    def _restore_latest(self):
        import jax

        d = self._latest_complete()
        if d is None:
            return
        # fault seam: restore-time read of this process's half of the cut
        faults.inject("dcn.ckpt.read", pid=self.pid)
        with open(os.path.join(d, f"proc-{self.pid}.meta.json")) as f:
            meta = json.load(f)
        data = np.load(os.path.join(d, f"proc-{self.pid}.npz"))
        leaves, treedef = jax.tree_util.tree_flatten(self.state)
        new_leaves = []
        for i, leaf in enumerate(leaves):
            new_leaves.append(jax.make_array_from_process_local_data(
                leaf.sharding, data[f"leaf{i}"]
            ))
        self.state = jax.tree_util.tree_unflatten(treedef, new_leaves)
        # emissions = concatenation of every delta up to (and including)
        # the restored cut; deltas past it belong to a globally
        # incomplete checkpoint and will be re-emitted by replay
        self.rows_key, self.rows_start = [], []
        self.rows_end, self.rows_val = [], []
        chosen = os.path.basename(d)
        for name in sorted(os.listdir(self.ckpt_dir)):
            if not name.startswith("chk-") or name > chosen:
                continue
            delta = np.load(os.path.join(
                self.ckpt_dir, name, f"proc-{self.pid}.npz"
            ))
            if len(delta["rows_key"]):
                self.rows_key.append(delta["rows_key"])
                self.rows_start.append(
                    delta["rows_start"] if "rows_start" in delta
                    else np.zeros(len(delta["rows_key"]), np.int64)
                )
                self.rows_end.append(delta["rows_end"])
                self.rows_val.append(delta["rows_val"])
        self._persisted_chunks = len(self.rows_key)
        self.cycle = int(meta["cycle"])
        self._next_cid = int(meta["next_cid"])
        self.local_wm_ticks = int(meta["wm_ticks"])
        self.source.restore(meta["source"])


class DCNWindowRunner(_DCNRunnerBase):
    """Aligned time windows (tumbling AND sliding via the pane ring) over
    the global mesh; the keyed shuffle is ONE all_to_all
    (RecordWriter.java:82 redesigned)."""

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        from flink_tpu.core.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from flink_tpu.ops import window_kernels as wk
        from flink_tpu.parallel.exchange import bucket_capacity
        from flink_tpu.parallel.mesh import SHARD_AXIS
        from flink_tpu.runtime.step import (
            WindowStageSpec,
            exchange_update_shard,
        )

        spec = self.spec
        n = self.n
        maxp = spec.max_parallelism
        if spec.size_ms <= 0:
            raise ValueError(
                "time-window DCN job requires size_ms > 0 "
                "(set DCNJobSpec.size_ms)"
            )
        slide = spec.slide_ms or spec.size_ms
        if spec.size_ms % slide:
            raise ValueError(
                f"size_ms {spec.size_ms} must be a multiple of slide_ms "
                f"{slide}"
            )
        ppw = spec.size_ms // slide
        # ring covers in-flight windows + out-of-orderness backlog (the
        # executor's sizing, executor.py setup())
        ring = max(8, 2 * ppw + spec.out_of_orderness_ms // slide + 4)
        self.win = wk.WindowSpec(
            size_ticks=spec.size_ms, slide_ticks=slide,
            ring=ring, fires_per_step=spec.fires_per_step,
        )
        self.red = wk.ReduceSpec(kind=spec.reduce_kind)
        win, red = self.win, self.red
        bpd = self.B_local // self.L    # lanes per device
        cap = bucket_capacity(bpd, n, 2.0)
        self.bucket_cap = cap
        starts, ends = self.ctx.kg_bounds()
        starts_j = jnp.asarray(starts)
        ends_j = jnp.asarray(ends)
        F = spec.fires_per_step
        C = spec.capacity_per_shard
        probe_len = 16
        mesh = self.ctx.mesh

        stage = WindowStageSpec(win=win, red=red, capacity_per_shard=C,
                                probe_len=probe_len)

        def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid,
                       wm, done):
            state = jax.tree_util.tree_map(lambda x: x[0], state)
            kg_start, kg_end = kg_start[0], kg_end[0]
            # global control values: decisions ride the same fabric as
            # records, so every process sees identical results and the
            # lockstep invariant holds by construction
            gwm = jax.lax.pmin(wm[0], SHARD_AXIS)
            gdone = jax.lax.pmin(done[0], SHARD_AXIS)
            # the cross-host keyed shuffle: ONE all_to_all over the
            # global mesh (RecordWriter.java:82 redesigned) — shared body
            # with the single-host exchange step (runtime/step.py)
            state, _ = exchange_update_shard(
                state, stage, kg_start, kg_end, hi, lo, ts, values, valid,
                n, maxp, cap,
            )
            state, fr = wk.advance_and_fire(state, win, red, gwm)
            cf = wk.compact_fires(state.table, fr)
            # fire backlog: a full on-time lane set means more window ends
            # may be due — every process must keep stepping
            pending = (jnp.sum(fr.lane_valid[:F], dtype=jnp.int32)
                       >= jnp.int32(F)).astype(jnp.int32)
            gpending = jax.lax.pmax(pending, SHARD_AXIS)
            stop = gdone * (1 - gpending)
            pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
            return pack(state), pack(cf), stop

        sharded = shard_map(
            shard_body, mesh=mesh,
            in_specs=(
                P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                # batch lanes are SPLIT over the global mesh: each host's
                # records sit on its local devices only
                P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS),
            ),
            out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
            check_vma=False,
        )

        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, hi, lo, ts, values, valid, wm, done):
            return sharded(state, starts_j, ends_j, hi, lo, ts, values,
                           valid, wm, done)

        self._step = step

        def sharded_init():
            st = wk.init_state(C, probe_len, win, red)
            return jax.tree_util.tree_map(lambda x: x[None], st)

        self._init_fn = jax.jit(shard_map(
            sharded_init, mesh=mesh, in_specs=(),
            out_specs=P(SHARD_AXIS), check_vma=False,
        ))
        self._mk_lane_sharding(mesh)

        if getattr(spec, "resident", False):
            # per-host resident mode (ISSUE 20b): same stage spec and
            # bucket capacity as the lockstep step — the drain IS the
            # lockstep body run up to resident_ring_depth times per
            # dispatch, with control collectives at the boundary
            from flink_tpu.runtime.step import (
                build_window_dcn_resident_drain,
            )

            self._resident_depth = max(1, int(spec.resident_ring_depth))
            self._drain = build_window_dcn_resident_drain(
                self.ctx, stage, bpd, self._resident_depth,
                capacity_factor=2.0,
            )

    def _emit_local_slots(self, cfs, drained: int):
        """Resident-drain fires: [n_shards, depth, ...] stacks — emit
        the first ``drained`` slots of each addressable shard in slot
        order (pad slots past the agreed count never fired)."""
        for (counts_sh, lanes_sh, ends_sh, khi_sh, klo_sh,
             vals_sh) in zip(
                cfs.counts.addressable_shards,
                cfs.lane_valid.addressable_shards,
                cfs.window_end_ticks.addressable_shards,
                cfs.key_hi.addressable_shards,
                cfs.key_lo.addressable_shards,
                cfs.values.addressable_shards):
            counts = np.asarray(counts_sh.data)[0]  # host-sync-ok: fire-payload fetch AFTER the drain retired
            lanes = np.asarray(lanes_sh.data)[0]  # host-sync-ok: fire-payload fetch
            ends = np.asarray(ends_sh.data)[0]  # host-sync-ok: fire-payload fetch
            khi = None
            for i in range(min(int(drained), counts.shape[0])):
                for f in np.nonzero(lanes[i])[0]:
                    c = int(counts[i, f])
                    if c == 0:
                        continue
                    if khi is None:
                        khi = np.asarray(khi_sh.data)[0]  # host-sync-ok: lazy key fetch, only when a slot fired
                        klo = np.asarray(klo_sh.data)[0]  # host-sync-ok: lazy key fetch
                        vv = np.asarray(vals_sh.data)[0]  # host-sync-ok: lazy value fetch
                    k64 = (khi[i, f, :c].astype(np.uint64)
                           << np.uint64(32)) \
                        | klo[i, f, :c].astype(np.uint64)
                    end_ms = int(ends[i, f]) + self.spec.origin_ms
                    self.rows_key.append(k64)
                    self.rows_start.append(
                        np.full(c, end_ms - self.spec.size_ms, np.int64))
                    self.rows_end.append(np.full(c, end_ms, np.int64))
                    self.rows_val.append(vv[i, f, :c].astype(np.float32))

    def _emit_local(self, cf):
        """Each process emits fires from ITS addressable shards only —
        "records enter on host A, fire from host B" is literal: the keys
        in these rows arrived via the all_to_all from whichever host
        ingested them."""
        size = self.spec.size_ms
        for (counts_sh, lanes_sh, ends_sh, khi_sh, klo_sh,
             vals_sh) in zip(
                cf.counts.addressable_shards,
                cf.lane_valid.addressable_shards,
                cf.window_end_ticks.addressable_shards,
                cf.key_hi.addressable_shards, cf.key_lo.addressable_shards,
                cf.values.addressable_shards):
            counts = np.asarray(counts_sh.data)[0]
            lanes = np.asarray(lanes_sh.data)[0]
            ends = np.asarray(ends_sh.data)[0]
            khi = None
            for f in np.nonzero(lanes)[0]:
                c = int(counts[f])
                if c == 0:
                    continue
                if khi is None:
                    khi = np.asarray(khi_sh.data)[0]
                    klo = np.asarray(klo_sh.data)[0]
                    vv = np.asarray(vals_sh.data)[0]
                k64 = (khi[f, :c].astype(np.uint64) << np.uint64(32)) \
                    | klo[f, :c].astype(np.uint64)
                end_ms = int(ends[f]) + self.spec.origin_ms
                self.rows_key.append(k64)
                self.rows_start.append(np.full(c, end_ms - size, np.int64))
                self.rows_end.append(np.full(c, end_ms, np.int64))
                self.rows_val.append(vv[f, :c].astype(np.float32))


class DCNSessionRunner(_DCNRunnerBase):
    """Gap-merged session windows over the global mesh. The session
    kernel is replicate-and-mask (ops/session_windows — every shard scans
    the batch and keeps its key groups), so the DCN hop is ONE
    ``all_gather`` of each host's lanes onto every shard; watermark and
    termination ride pmin exactly like the time-window runner. Sessions
    merging records from DIFFERENT hosts is the point: the gap merge
    happens in the owning shard's device state wherever the records
    entered."""

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        from flink_tpu.core.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from flink_tpu.ops import session_windows as sw
        from flink_tpu.ops import window_kernels as wk
        from flink_tpu.ops.hashing import route_hash
        from flink_tpu.core.keygroups import assign_to_key_group
        from flink_tpu.parallel.mesh import SHARD_AXIS

        spec = self.spec
        if spec.gap_ms <= 0:
            raise ValueError("session DCN job requires gap_ms > 0")
        maxp = spec.max_parallelism
        self.red = wk.ReduceSpec(kind=spec.reduce_kind)
        red = self.red
        gap = spec.gap_ms
        starts, ends = self.ctx.kg_bounds()
        starts_j = jnp.asarray(starts)
        ends_j = jnp.asarray(ends)
        C = spec.capacity_per_shard
        probe_len = 16
        mesh = self.ctx.mesh

        def shard_body(state, kg_start, kg_end, hi, lo, ts, values, valid,
                       wm, done):
            state = jax.tree_util.tree_map(lambda x: x[0], state)
            kg_start, kg_end = kg_start[0], kg_end[0]
            gwm = jax.lax.pmin(wm[0], SHARD_AXIS)
            gdone = jax.lax.pmin(done[0], SHARD_AXIS)
            # the DCN hop: every shard sees every host's lanes (the
            # replicate side of replicate-and-mask; traffic-equivalent to
            # the single-host step's replicated batch feed)
            hi_g = jax.lax.all_gather(hi, SHARD_AXIS, tiled=True)
            lo_g = jax.lax.all_gather(lo, SHARD_AXIS, tiled=True)
            ts_g = jax.lax.all_gather(ts, SHARD_AXIS, tiled=True)
            va_g = jax.lax.all_gather(values, SHARD_AXIS, tiled=True)
            ok_g = jax.lax.all_gather(valid, SHARD_AXIS, tiled=True)
            kg = assign_to_key_group(route_hash(hi_g, lo_g, jnp), maxp,
                                     jnp)
            mine = ok_g & (kg >= kg_start.astype(jnp.uint32)) & (
                kg <= kg_end.astype(jnp.uint32)
            )
            state, old_f, mid_f, wm_f = sw.update_and_fire(
                state, red, gap, hi_g, lo_g, ts_g, va_g, mine, gwm
            )
            # slot-space wm fires carry no keys — attach them here so the
            # host never needs the (donated) state
            wkeys = state.table.keys
            wm_out = (wkeys[:, 0], wkeys[:, 1]) + tuple(wm_f)
            # any records this step? sessions opened by the final batch
            # need ONE empty follow-up step at wm=MAX to flush, so stop
            # only on a globally record-free exhausted step
            has_rec = jnp.any(ok_g).astype(jnp.int32)
            stop = gdone * (1 - jax.lax.pmax(has_rec, SHARD_AXIS))
            pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
            return (pack(state), (pack(old_f), pack(mid_f), pack(wm_out)),
                    stop)

        sharded = shard_map(
            shard_body, mesh=mesh,
            in_specs=(
                P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS),
            ),
            out_specs=(
                P(SHARD_AXIS),
                (P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
                P(),
            ),
            check_vma=False,
        )

        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, hi, lo, ts, values, valid, wm, done):
            return sharded(state, starts_j, ends_j, hi, lo, ts, values,
                           valid, wm, done)

        self._step = step

        def sharded_init():
            st = sw.init_state(C, probe_len, red)
            return jax.tree_util.tree_map(lambda x: x[None], st)

        self._init_fn = jax.jit(shard_map(
            sharded_init, mesh=mesh, in_specs=(),
            out_specs=P(SHARD_AXIS), check_vma=False,
        ))
        self._mk_lane_sharding(mesh)

    def _emit_local(self, aux):
        """Session fires from this process's addressable shards: two
        lane-space sets (superseded/merged) carrying their own keys, plus
        the slot-space watermark-close set keyed by the table rows."""
        old_f, mid_f, wm_out = aux
        origin = self.spec.origin_ms
        for fire in (old_f, mid_f):
            khi_l, klo_l, st_l, en_l, va_l, mk_l = (
                a.addressable_shards for a in fire
            )
            for khi_s, klo_s, st_s, en_s, va_s, mk_s in zip(
                    khi_l, klo_l, st_l, en_l, va_l, mk_l):
                mask = np.asarray(mk_s.data)[0]
                sel = np.nonzero(mask)[0]
                if not sel.size:
                    continue
                khi = np.asarray(khi_s.data)[0][sel]
                klo = np.asarray(klo_s.data)[0][sel]
                self._push_rows(
                    khi, klo,
                    np.asarray(st_s.data)[0][sel],
                    np.asarray(en_s.data)[0][sel],
                    np.asarray(va_s.data)[0][sel], origin,
                )
        wkhi_l, wklo_l, st_l, en_l, va_l, mk_l = (
            a.addressable_shards for a in wm_out
        )
        for khi_s, klo_s, st_s, en_s, va_s, mk_s in zip(
                wkhi_l, wklo_l, st_l, en_l, va_l, mk_l):
            mask = np.asarray(mk_s.data)[0]
            sel = np.nonzero(mask)[0]
            if not sel.size:
                continue
            self._push_rows(
                np.asarray(khi_s.data)[0][sel],
                np.asarray(klo_s.data)[0][sel],
                np.asarray(st_s.data)[0][sel],
                np.asarray(en_s.data)[0][sel],
                np.asarray(va_s.data)[0][sel], origin,
            )

    def _push_rows(self, khi, klo, starts, ends, vals, origin):
        k64 = (khi.astype(np.uint64) << np.uint64(32)) | klo.astype(
            np.uint64)
        self.rows_key.append(k64)
        self.rows_start.append(starts.astype(np.int64) + origin)
        # kernel fire `end` is already last + gap (session TimeWindow
        # semantics, ops/session_windows.update_and_fire docstring)
        self.rows_end.append(ends.astype(np.int64) + origin)
        self.rows_val.append(vals.astype(np.float32))


class DCNRollingRunner(_DCNRunnerBase):
    """Rolling keyed reduce (the reference's StreamGroupedReduce on
    ValueState) over the global mesh: records route to their owner shard
    through the SAME one-collective keyed shuffle as the window runners
    (exchange_records), the owner applies the running reduce, and the
    per-record UPDATED aggregate emits from the owner shard. Per-key
    emission order equals per-key arrival order on the owning channel —
    the reference's partition-order guarantee; there is no cross-key
    global order, exactly as in the reference. Closes the "rolling
    cannot run multi-host" gap (VERDICT r4 missing #4 tail)."""

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        from flink_tpu.core.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from flink_tpu.ops import rolling
        from flink_tpu.ops import window_kernels as wk
        from flink_tpu.parallel.exchange import (
            bucket_capacity,
            exchange_owned,
        )
        from flink_tpu.parallel.mesh import SHARD_AXIS

        spec = self.spec
        n = self.n
        maxp = spec.max_parallelism
        red = wk.ReduceSpec(kind=spec.reduce_kind)
        C = spec.capacity_per_shard
        probe_len = 16
        bpd = self.B_local // self.L
        cap = bucket_capacity(bpd, n, 2.0)
        self.bucket_cap = cap
        starts, ends = self.ctx.kg_bounds()
        starts_j = jnp.asarray(starts)
        ends_j = jnp.asarray(ends)
        mesh = self.ctx.mesh

        def shard_body(state, kg_start, kg_end, hi, lo, ts, values,
                       valid, wm, done):
            import dataclasses as _dc

            state = jax.tree_util.tree_map(lambda x: x[0], state)
            kg_start, kg_end = kg_start[0], kg_end[0]
            gdone = jax.lax.pmin(done[0], SHARD_AXIS)
            cols, r_hi, r_lo, mine, n_over = exchange_owned(
                {"values": values}, hi, lo, valid, n, maxp, cap,
                kg_start, kg_end,
            )
            state, outputs, out_valid = rolling.update(
                state, red, r_hi, r_lo, cols["values"], mine
            )
            # exchange-bucket overflow folds into the CHECKPOINTED state
            # counter alongside rolling.update's own table-full drops
            # (runtime/step.py:exchange_update_shard does the same) —
            # surfaced at run end via _state_dropped, surviving restore
            state = _dc.replace(
                state,
                dropped_capacity=state.dropped_capacity + n_over,
            )
            pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
            aux = (r_hi, r_lo, outputs, out_valid)
            # rolling has no fire backlog: the ensemble stops when every
            # source is drained
            return pack(state), pack(aux), gdone

        sharded = shard_map(
            shard_body, mesh=mesh,
            in_specs=(
                P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS),
            ),
            out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
            check_vma=False,
        )

        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, hi, lo, ts, values, valid, wm, done):
            return sharded(state, starts_j, ends_j, hi, lo, ts, values,
                           valid, wm, done)

        self._step = step

        def sharded_init():
            st = rolling.init_state(C, probe_len, red)
            return jax.tree_util.tree_map(lambda x: x[None], st)

        self._init_fn = jax.jit(shard_map(
            sharded_init, mesh=mesh, in_specs=(),
            out_specs=P(SHARD_AXIS), check_vma=False,
        ))
        self._mk_lane_sharding(mesh)

    def _emit_local(self, aux):
        """Emit (key, updated aggregate) per exchanged record from THIS
        process's shards. window_start/end are 0: rolling emissions are
        continuous per-record updates, not window results."""
        r_hi, r_lo, outputs, out_valid = aux
        for hi_sh, lo_sh, out_sh, val_sh in zip(
                r_hi.addressable_shards, r_lo.addressable_shards,
                outputs.addressable_shards, out_valid.addressable_shards):
            mask = np.asarray(val_sh.data)[0]
            idx = np.nonzero(mask)[0]
            if not len(idx):
                continue
            khi = np.asarray(hi_sh.data)[0][idx]
            klo = np.asarray(lo_sh.data)[0][idx]
            vals = np.asarray(out_sh.data)[0][idx]
            k64 = (khi.astype(np.uint64) << np.uint64(32)) \
                | klo.astype(np.uint64)
            self.rows_key.append(k64)
            self.rows_start.append(np.zeros(len(idx), np.int64))
            self.rows_end.append(np.zeros(len(idx), np.int64))
            self.rows_val.append(vals.astype(np.float32))


class DCNCEPRunner(_DCNRunnerBase):
    """Device count-NFA pattern matching over the global mesh — CEP
    multi-host, the last stage kind on VERDICT r4's cannot-run-multi-
    host list. Replicate-and-mask like the session runner: ONE
    all_gather puts every host's lanes on every shard, each shard
    advances the NFA for its own key groups (cep/device.py's segmented
    matrix scan), and match completions emit from the OWNER shard.
    Cross-host event order is the deterministic lockstep lane order
    (cycle-major, host-major) — the processing-time arrival-order
    semantics of the reference's operator. Stage predicates evaluate at
    the INGESTING host (bits packed in the value lane, see DCNJobSpec);
    the device carries only the bit masks, so arbitrary Python
    conditions cost nothing on the accelerator. within() is not carried
    here yet: its pane ring needs pane-quantized batches (cep/accel.py's
    host slicing), which the lockstep loop does not do — a
    pattern.within_ms raises rather than silently ignoring the bound.
    Match EXTRACTION stays host-side per the single-host engine's lazy
    replay; rows here are (key, completion ts, completions-at-event) —
    the match-count stream the CEP bench measures."""

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        from flink_tpu.core.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from flink_tpu.cep import device as cdev
        from flink_tpu.core.keygroups import assign_to_key_group
        from flink_tpu.ops.hashing import route_hash
        from flink_tpu.parallel.mesh import SHARD_AXIS

        spec = self.spec
        if spec.cep_pattern_factory is None:
            raise ValueError(
                "cep DCN job requires DCNJobSpec.cep_pattern_factory"
            )
        pattern = spec.cep_pattern_factory()
        if getattr(pattern, "within_ms", None):
            raise ValueError(
                "within() is not supported on the DCN CEP runner yet "
                "(needs pane-quantized batches); run single-host via "
                "cep/accel.py or drop the within bound"
            )
        dspec = cdev.DevicePatternSpec.from_pattern(pattern)
        S = dspec.n_stages
        if S > 24:
            raise ValueError(
                f"{S} stages exceed the 24 mask bits a float32 value "
                f"lane carries exactly"
            )
        maxp = spec.max_parallelism
        C = spec.capacity_per_shard
        probe_len = 16
        starts, ends = self.ctx.kg_bounds()
        starts_j = jnp.asarray(starts)
        ends_j = jnp.asarray(ends)
        mesh = self.ctx.mesh

        def shard_body(state, kg_start, kg_end, hi, lo, ts, values,
                       valid, wm, done):
            state = jax.tree_util.tree_map(lambda x: x[0], state)
            kg_start, kg_end = kg_start[0], kg_end[0]
            gdone = jax.lax.pmin(done[0], SHARD_AXIS)
            # the DCN hop: every shard sees every host's lanes
            hi_g = jax.lax.all_gather(hi, SHARD_AXIS, tiled=True)
            lo_g = jax.lax.all_gather(lo, SHARD_AXIS, tiled=True)
            ts_g = jax.lax.all_gather(ts, SHARD_AXIS, tiled=True)
            va_g = jax.lax.all_gather(values, SHARD_AXIS, tiled=True)
            ok_g = jax.lax.all_gather(valid, SHARD_AXIS, tiled=True)
            bits = va_g.astype(jnp.int32)
            masks = ((bits[:, None] >> jnp.arange(S, dtype=jnp.int32))
                     & 1).astype(bool)
            kg = assign_to_key_group(route_hash(hi_g, lo_g, jnp), maxp,
                                     jnp)
            mine = ok_g & (kg >= kg_start.astype(jnp.uint32)) & (
                kg <= kg_end.astype(jnp.uint32)
            )
            state, delta, _total = cdev.advance(
                state, dspec, hi_g, lo_g, masks, mine
            )
            pack = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
            aux = (hi_g, lo_g, ts_g, delta)
            # count-NFA matches complete on arrival: nothing flushes at
            # end of stream, so stop when every source is drained
            return pack(state), pack(aux), gdone

        sharded = shard_map(
            shard_body, mesh=mesh,
            in_specs=(
                P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS),
                P(SHARD_AXIS), P(SHARD_AXIS),
            ),
            out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
            check_vma=False,
        )

        from functools import partial

        @partial(jax.jit, donate_argnums=(0,))
        def step(state, hi, lo, ts, values, valid, wm, done):
            return sharded(state, starts_j, ends_j, hi, lo, ts, values,
                           valid, wm, done)

        self._step = step

        def sharded_init():
            st = cdev.init_state(C, probe_len, dspec)
            return jax.tree_util.tree_map(lambda x: x[None], st)

        self._init_fn = jax.jit(shard_map(
            sharded_init, mesh=mesh, in_specs=(),
            out_specs=P(SHARD_AXIS), check_vma=False,
        ))
        self._mk_lane_sharding(mesh)

    def _emit_local(self, aux):
        """Emit (key, completion ts, matches-completed-at-event) from
        THIS process's shards — deltas are nonzero only on lanes whose
        key the shard owns."""
        hi_g, lo_g, ts_g, delta = aux
        origin = self.spec.origin_ms
        for hi_sh, lo_sh, ts_sh, d_sh in zip(
                hi_g.addressable_shards, lo_g.addressable_shards,
                ts_g.addressable_shards, delta.addressable_shards):
            d = np.asarray(d_sh.data)[0]
            idx = np.nonzero(d)[0]
            if not len(idx):
                continue
            khi = np.asarray(hi_sh.data)[0][idx]
            klo = np.asarray(lo_sh.data)[0][idx]
            ts = np.asarray(ts_sh.data)[0][idx]
            k64 = (khi.astype(np.uint64) << np.uint64(32)) \
                | klo.astype(np.uint64)
            self.rows_key.append(k64)
            self.rows_start.append(np.zeros(len(idx), np.int64))
            self.rows_end.append(ts.astype(np.int64) + origin)
            self.rows_val.append(d[idx].astype(np.float32))


def runner_for_spec(spec: DCNJobSpec, process_id: int, num_processes: int,
                    **kw) -> _DCNRunnerBase:
    if spec.window_kind == "session":
        return DCNSessionRunner(spec, process_id, num_processes, **kw)
    if spec.window_kind == "time":
        return DCNWindowRunner(spec, process_id, num_processes, **kw)
    if spec.window_kind == "rolling":
        return DCNRollingRunner(spec, process_id, num_processes, **kw)
    if spec.window_kind == "cep":
        return DCNCEPRunner(spec, process_id, num_processes, **kw)
    raise ValueError(f"unknown window_kind {spec.window_kind!r}")


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True, help="HOST:PORT")
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--builder", required=True,
                    help="module:function returning a DCNJobSpec")
    ap.add_argument("--out", required=True, help="result .npz path")
    ap.add_argument("--checkpoint-dir", default="")
    ap.add_argument("--ckpt-every", type=int, default=0)
    ap.add_argument("--restore", action="store_true")
    a = ap.parse_args(argv)

    plat = os.environ.get("JAX_PLATFORMS")
    import jax

    if plat:
        jax.config.update("jax_platforms", plat)
    jax.distributed.initialize(
        coordinator_address=a.coordinator,
        num_processes=a.num_processes, process_id=a.process_id,
    )
    from flink_tpu.runtime.worker import load_builder

    spec = load_builder(a.builder)()
    runner = runner_for_spec(
        spec, a.process_id, a.num_processes,
        checkpoint_dir=a.checkpoint_dir or None,
        ckpt_every=a.ckpt_every, restore=a.restore,
    )
    out = runner.run()
    tmp = a.out + ".tmp"
    # lint: allow(fault-seam): one-shot CLI result dump after the job ended — not a recovery seam; a failure here is an ordinary process error
    with open(tmp, "wb") as f:    # file object: savez appends no suffix
        np.savez(f, key_id=out["key_id"],
                 window_start_ms=out["window_start_ms"],
                 window_end_ms=out["window_end_ms"], value=out["value"],
                 dropped_capacity=out["dropped_capacity"])
    # lint: allow(fault-seam): same one-shot result publish as the open above
    os.replace(tmp, a.out)
    print(json.dumps({"rows": int(len(out["key_id"])),
                      "cycles": out["cycles"], "pid": a.process_id,
                      "dropped_capacity": out["dropped_capacity"],
                      "ingested_local": int(out["ingested_local"])}),
          flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
